"""SolveSession: a resilient microbatching front door for same-pattern solves.

The serving loop this subsystem exists for: requests ``(A-values, b,
tol)`` trickle in from many callers, almost all of them over a handful
of sparsity patterns (the deployed meshes/graphs). The session queues
them, coalesces same-pattern requests into bucketed batches
(:mod:`sparse_tpu.batch.bucket`), dispatches each bucket through ONE
compiled masked-Krylov program (:mod:`sparse_tpu.batch.krylov`), and
scatters per-lane results back to their tickets.

Compile-count control is the whole game: the per-bucket program — the
pattern's packed SELL matvec closed inside a jitted solver loop — lives
in :mod:`sparse_tpu.plan_cache` keyed ``(pattern, "batch.<solver>.B<bucket>...")``,
so a bucket costs exactly ONE cache miss (pack + trace + compile) ever,
and every later dispatch of that bucket is a cache hit straight into a
warm executable. ``plan_cache.stats()`` is the always-on instrument;
with telemetry enabled each dispatch additionally emits a
``batch.dispatch`` event (batch size, bucket, padding waste, queue
latency, per-lane iteration stats — docs/batching.md).

Resilience (ISSUE 5, docs/resilience.md): tickets carry an explicit
:class:`TicketState` and per-ticket deadlines; ``flush()`` is
exception-safe (one failed bucket program marks ITS tickets failed and
every other bucket still dispatches); lanes that come back unconverged
or nonfinite requeue ONCE into a fallback bucket (safer solver —
default GMRES — at a promoted dtype), emitting ``batch.requeue``; and
when the compiled-program path itself is unavailable (Pallas lowering
gone, plan-cache failure, injected dispatch faults) the bucket degrades
to per-lane eager solves rather than stranding its tickets
(``batch.degraded``).

Fleet serving tier (ISSUE 10, docs/batching.md "Serving across a
mesh"): with ``SPARSE_TPU_FLEET=auto`` (or ``fleet=`` at construction)
a per-(pattern, bucket) policy (:mod:`sparse_tpu.fleet`) shards
dispatches over the device mesh — same-pattern buckets batch-shard
their lane stacks across the mesh batch axis (per-lane results
bit-identical, the all-converged exit a measured lane-count psum),
single oversized systems row-shard through ``DistCSR``/``dist_cg`` as
B=1 bucket programs. Program keys gain the mesh fingerprint, vault
manifest entries record it (a different-topology restart cold-starts
cleanly), and ``session_stats()`` reports the mesh shape plus
per-device lane occupancy.

Streaming dispatch (ISSUE 13, docs/batching.md "Streaming dispatch"):
the session is a real pipeline, not an enqueue->block loop. Dispatch is
non-blocking — ``flush(wait=False)`` (and the ``auto_flush`` fast path)
enqueues bucket programs without ``block_until_ready`` behind a bounded
in-flight window (``SPARSE_TPU_INFLIGHT``, default 2), so the host
packs/uploads bucket N+1 (``bucket.stage_lanes``: pad + eager
``jax.device_put``) while the device solves bucket N; on TPU/GPU the
bucket programs additionally donate their value-stack/rhs/x0 buffers.
Readback is deferred: :class:`SolveTicket` is future-style
(``ready`` / ``result(timeout=)``), and scatter/unpack/terminal
accounting run lazily when results are awaited, at ``poll()`` (retire
whatever already finished), or at ``drain()``. Admission control rides
the same machinery: ``max_queue_depth`` applies backpressure at
``submit`` (block or reject, ``batch.admission`` events) keyed off the
``batch.queue_depth`` gauge's depth accounting, per-ticket deadlines
are re-checked at readback (a lane gone stale in flight never spends a
requeue past its deadline), and the vault warm replay runs on a
background thread so a restarted process serves immediately —
dispatches of a program the replay is still compiling wait for that
program instead of rebuilding it. ``SPARSE_TPU_INFLIGHT=1`` reproduces
the classic synchronous path bit-identically (pinned by
``tests/test_pipeline.py``).

Request-scoped observability (ISSUE 6, Axon v3): every ticket carries a
process-unique id (``telemetry.new_ticket_id``); each dispatch runs
inside a :func:`telemetry.ticket_scope` so EVERY event it causes —
``batch.dispatch``, a ``kernel.failover`` five layers down,
``fault.injected``, ``batch.requeue`` — carries the originating ids;
and flush resolution emits one ``batch.ticket`` terminal event per
request with the end-to-end latency and its phase breakdown (queue wait
→ pack → compile → solve → readback). Latencies feed the always-on
``batch.ticket_latency`` histogram (per solver) and, when the session
has an ``slo_ms`` target, the ``batch.slo_misses`` counter — the
percentiles/SLO surface ``scripts/axon_report.py`` rolls up and the
live exporter (``telemetry.serve()``) scrapes. Bucket-program builds
route through :mod:`telemetry._cost <sparse_tpu.telemetry._cost>` so
each (pattern, solver, bucket, dtype) program's compile wall-clock and
XLA cost/memory analysis land in ``plan_cache.compile`` events.
"""

from __future__ import annotations

import collections
import enum
import threading
import time
import weakref
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .. import autopilot as autopilot_mod
from .. import fleet as fleet_mod
from ..fleet import elastic as elastic_mod
from .. import mixed as mixed_mod
from .. import plan_cache, telemetry
from .. import precond as precond_mod
from ..config import settings
from ..ops import spmv as spmv_ops
from ..parallel import comm as _comm
from ..resilience import faults as _faults
from ..resilience.policy import deadline_remaining_s
from ..telemetry import _budget, _cost, _history, _metrics, _profiler
from . import bucket as bucketing
from . import krylov
from .operator import BatchedCSR, SparsityPattern

_SOLVERS = ("cg", "bicgstab", "gmres")

# Always-on session levels (telemetry/_metrics.py — scrapeable via
# telemetry.metrics_text()): queued-request depth across all live
# sessions, real-lanes-per-bucket occupancy ratio, and dispatch count.
_QUEUE_DEPTH = _metrics.gauge("batch.queue_depth")
_BUCKET_OCCUPANCY = _metrics.histogram("batch.bucket_occupancy")
_DISPATCHES = _metrics.counter("batch.dispatches")
_PAD_WASTE = _metrics.counter("batch.pad_lanes")
# resilience levels
_REQUEUES = _metrics.counter("batch.requeues")
_DEGRADED = _metrics.counter("batch.degraded")
_BUCKET_FAILURES = _metrics.counter("batch.bucket_failures")
_DEADLINE_FAILED = _metrics.counter("batch.deadline_failed")
# serving levels (ISSUE 6): end-to-end ticket latency (seconds, per
# final solver) and SLO misses across all sessions with an slo_ms target
_SLO_MISSES = _metrics.counter(
    "batch.slo_misses",
    help="tickets whose end-to-end latency exceeded the session slo_ms",
)
_TICKET_LATENCY_HELP = (
    "end-to-end ticket latency in seconds (submit -> resolved)"
)
# streaming-dispatch levels (ISSUE 13): bucket programs currently in
# flight on the device (dispatched, not yet retired) and lanes whose
# requeue was skipped because their deadline passed while in flight
_INFLIGHT = _metrics.gauge(
    "batch.inflight",
    help="bucket programs dispatched and not yet retired (the streaming "
    "pipeline's in-flight window depth)",
)
_STALE_REQUEUES = _metrics.counter(
    "batch.stale_requeues",
    help="unconverged lanes whose requeue was skipped at readback "
    "because the ticket deadline had already passed",
)
# elastic-mesh levels (ISSUE 20): executed topology transitions by
# outcome — 'ok' (quiesce -> retarget -> replay completed) or 'latched'
# (the flap guard refused and pinned the single-device strategy)
_REMESHES_HELP = (
    "executed elastic topology transitions, by outcome "
    "('ok' | 'latched')"
)

# live sessions, weakly held: the /session serving endpoint
# (telemetry/_serve.py) reads their stats without keeping them alive
_SESSIONS: "weakref.WeakSet" = weakref.WeakSet()


def sessions_stats() -> list:
    """``session_stats()`` of every live session (the ``/session``
    exporter endpoint's payload; order is not meaningful)."""
    return [s.session_stats() for s in list(_SESSIONS)]


class TicketState(enum.Enum):
    """Lifecycle of a submitted system (ISSUE 5 satellite: unresolved
    and failed tickets used to be indistinguishable bare RuntimeErrors)."""

    PENDING = "pending"
    DONE = "done"
    FAILED = "failed"


class TicketError(RuntimeError):
    """Base of the ticket error family."""


class TicketUnresolvedError(TicketError):
    """``result()`` on a ticket no flush has resolved (should not happen
    through the public API — flush resolves or fails every ticket)."""


class TicketFailedError(TicketError):
    """The ticket's bucket failed (program error, exhausted dispatch
    retries); ``__cause__`` carries the underlying exception."""


class TicketDeadlineError(TicketFailedError):
    """The ticket's deadline passed before its bucket dispatched."""


class TicketTimeoutError(TicketError):
    """``result(timeout=)`` lapsed before the ticket resolved. The
    ticket stays PENDING — a later ``result()`` (or ``drain()``) still
    retires it normally; a timeout never loses work."""


class AdmissionError(RuntimeError):
    """``submit`` refused a request: the session's ``max_queue_depth``
    backpressure threshold was reached under ``admission='reject'``
    (``batch.admission`` event; docs/batching.md "Streaming dispatch")."""


class InjectedDispatchFailure(RuntimeError):
    """A ``drop:dispatch`` fault clause fired (resilience.faults) — the
    injected stand-in for a dispatch lost to a worker/backend failure."""


class SolveTicket:
    """Future-style handle for one submitted system. ``result()``
    dispatches the request if it is still queued, retires its bucket
    (and any bucket ahead of it in the in-flight window) if it is in
    flight, then returns ``(x, iters, resid2)`` (host numpy
    scalars/arrays for the lane). ``result(timeout=s)`` waits at most
    ``s`` seconds and raises :class:`TicketTimeoutError` (the ticket
    stays pending and retains its place in the pipeline); ``ready`` is
    the non-blocking probe — True once the result can be fetched
    without waiting on the device. Failed tickets raise
    :class:`TicketFailedError` (:class:`TicketDeadlineError` for
    deadline misses) instead of returning garbage.

    ``id`` is the process-unique trace id every event the ticket causes
    carries (``telemetry.ticket_scope``); ``phase_ms`` accumulates the
    per-phase latency breakdown (queue/pack/compile/solve/readback)
    across the first dispatch and any requeue, and is what the
    ``batch.ticket`` terminal event and the Perfetto ticket lane render.

    ``tenant`` is the optional caller label fairness rollups group by
    (ISSUE 11 satellite): it rides the ``batch.ticket`` terminal event
    and labels the ``batch.ticket_latency`` histogram; ``None`` (the
    default) keeps the existing metric series names unchanged."""

    __slots__ = ("_session", "_out", "t_submit", "state", "error",
                 "deadline_s", "requeued", "solver", "id", "phase_ms",
                 "t_done", "t_mark", "tenant", "promoted", "dtype_policy")

    def __init__(self, session, deadline_s=None, tenant=None):
        self._session = session
        self._out = None
        self.t_submit = time.monotonic()
        self.state = TicketState.PENDING
        self.error = None
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.requeued = False
        self.solver = None  # the solver that produced the final result
        self.id = telemetry.new_ticket_id()
        self.phase_ms: dict = {}
        self.t_done = None  # set once, at first terminal resolution
        self.t_mark = None  # end of the last phase-accounted dispatch
        self.tenant = None if tenant is None else str(tenant)
        # mixed precision (ISSUE 15): whether the promote_dtype rung
        # already re-solved this lane at 'exact', and the reduced
        # policy the lane last dispatched under (None = exact — keeps
        # metric series names and event fields unchanged)
        self.promoted = False
        self.dtype_policy = None

    @property
    def done(self) -> bool:
        return self.state is TicketState.DONE

    @property
    def failed(self) -> bool:
        return self.state is TicketState.FAILED

    @property
    def expired(self) -> bool:
        return (
            self.deadline_s is not None
            and deadline_remaining_s(self.t_submit, self.deadline_s) <= 0
        )

    def _offer(self, x, iters, resid2, converged, solver=None):
        """Install a result, keeping the better one when a fallback
        dispatch re-solves the lane (converged beats unconverged, then
        smaller residual; a FAILED ticket is revived by any result)."""
        new = (x, int(iters), float(resid2), bool(converged))
        if self._out is not None:
            old = self._out
            better = (new[3] and not old[3]) or (
                new[3] == old[3]
                and (np.isfinite(new[2]) and not np.isfinite(old[2])
                     or (np.isfinite(new[2]) and np.isfinite(old[2])
                         and new[2] < old[2]))
            )
            if not better:
                return
        self._out = new
        self.state = TicketState.DONE
        self.error = None
        if solver is not None:
            self.solver = solver

    def _fail(self, exc) -> None:
        if self.state is TicketState.DONE:
            return  # a resolved ticket never regresses to failed
        self.state = TicketState.FAILED
        self.error = exc

    @property
    def ready(self) -> bool:
        """True when ``result()`` would return (or raise) without
        waiting on the device: the ticket is terminal, or its bucket's
        in-flight outputs are already materialized. Never blocks and
        never advances the pipeline."""
        if self.state is not TicketState.PENDING:
            return True
        return self._session._ticket_ready(self)

    def result(self, timeout: float | None = None):
        if self.state is TicketState.PENDING:
            self._session._resolve_ticket(self, timeout)
        if self.state is TicketState.PENDING:
            if self._session._holds(self):
                raise TicketTimeoutError(
                    f"ticket not resolved within {timeout}s (still "
                    "queued/in flight; result() again to keep waiting)"
                )
            raise TicketUnresolvedError(
                "flush did not resolve this ticket"
            )
        if self.state is TicketState.FAILED:
            raise (
                self.error
                if isinstance(self.error, TicketError)
                else TicketFailedError(
                    f"bucket dispatch failed: {self.error!r}"
                )
            ) from (self.error if isinstance(self.error, Exception) else None)
        if self._out is None:
            raise TicketUnresolvedError(
                "flush did not resolve this ticket"
            )
        return self._out[:3]

    @property
    def converged(self) -> bool:
        if self.state is TicketState.PENDING:
            self._session._resolve_ticket(self, None)
        if self._out is None:
            return False
        return self._out[3]


class _Request:
    __slots__ = ("pattern", "values", "b", "tol", "x0", "maxiter", "ticket",
                 "precond", "dtype_policy", "precond_dtype")

    def __init__(self, pattern, values, b, tol, x0, maxiter, ticket,
                 precond=None, dtype_policy=None, precond_dtype=None):
        self.pattern, self.values, self.b = pattern, values, b
        self.tol, self.x0, self.maxiter = tol, x0, maxiter
        self.ticket = ticket
        # per-ticket preconditioner override (ISSUE 14): None = the
        # session policy decides; a canonical kind/'none' forces it.
        # Joins the flush group key — lanes with different overrides
        # never share a bucket program.
        self.precond = precond
        # per-ticket dtype-policy override (ISSUE 15): same contract —
        # None = session policy, a canonical policy/'exact' forces it,
        # and it joins the flush group key like the precond override.
        self.dtype_policy = dtype_policy
        # per-ticket precond storage-dtype override (ISSUE 16): None =
        # session/env, 'compute'/'storage' forces; same grouping/keying
        # contract as the two overrides above.
        self.precond_dtype = precond_dtype


def _promote(dt: np.dtype) -> np.dtype:
    """The requeue bucket's 'safer dtype': one precision step up."""
    dt = np.dtype(dt)
    if dt == np.float32:
        return np.dtype(np.float64)
    if dt == np.complex64:
        return np.dtype(np.complex128)
    return dt


def donate_argnums() -> tuple:
    """``donate_argnums`` for the bucket programs' value-stack/rhs/x0
    arguments (ISSUE 13): on TPU/GPU donation lets XLA recycle the
    uploaded input HBM for outputs/temps — with streaming dispatch up
    to ``SPARSE_TPU_INFLIGHT`` buckets hold buffers concurrently, so
    the recycling halves the transient footprint. CPU has no donation
    lowering (jax warns per call), so the CPU lane compiles the
    IDENTICAL program with no donation — jaxprs and results are
    unchanged either way (docs/batching.md, donation caveats)."""
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 - no backend yet: donate nothing
        return ()
    return (0, 1, 2) if backend in ("tpu", "gpu", "cuda", "rocm") else ()


def _build_ir_program(pack, mixed: dict, solver: str, cti: int, mfac,
                      precond_dtype: str = "compute"):
    """The reduced-precision bucket program (ISSUE 15): values downcast
    once inside the program — f64 planes for the outer residual, the
    policy's storage-width planes for the inner sweep (wide-accumulating
    matvec via ``acc_dtype``) — around the fused iterative-refinement
    loop (:func:`sparse_tpu.mixed.ir_loop`). Same argument signature as
    the exact bucket programs; one extra output (the refinement sweep
    count).

    ``precond_dtype='storage'`` (ISSUE 16) hands the preconditioner
    factory the STORAGE-width value stack instead of the compute-width
    one — paired with a storage-dtype factory (``PrecondPolicy.factory``
    with ``storage_dtype``/``acc_dtype``) the factors then live at the
    reduced width with wide accumulation, compounding the mixed path's
    memory-traffic win into M. 'compute' is byte-identical to the
    historic program."""
    idx_slabs, pos, zero_rows = (
        pack.idx_slabs, pack.pos, pack.plan.zero_rows
    )
    storage_dt, compute_dt = mixed_mod.inner_dtypes(mixed["policy"])
    sdt = jnp.dtype(storage_dt)
    cdt = jnp.dtype(compute_dt)
    wdt = jnp.dtype(mixed_mod.outer_dtype())
    inner_iters = int(mixed["inner_iters"])
    max_outer = int(mixed["max_outer"])
    eta = float(mixed["eta"])

    @partial(jax.jit, donate_argnums=donate_argnums())
    def run(values, rhs, x0, tols, maxiter):
        req_dt = values.dtype
        vals_w = pack.pack_values(values.astype(wdt))
        vals_l = pack.pack_values(values.astype(sdt))

        def mv_wide(X):
            return spmv_ops.csr_spmv_sell_batched(
                idx_slabs, vals_w, pos, X, zero_rows
            )

        def mv_low(X):
            return spmv_ops.csr_spmv_sell_batched(
                idx_slabs, vals_l, pos, X, zero_rows, acc_dtype=cdt
            )

        fmv_low = krylov._maybe_faulty_mv(mv_low)
        # batched numeric factorization (ISSUE 15/16): the factory sees
        # the COMPUTE-width values by default (application widened
        # consistently with the inner sweep); under precond_dtype=
        # 'storage' it sees the STORAGE-width stack and the storage-
        # dtype factory keeps the factors narrow with wide accumulation
        mdt = sdt if precond_dtype == "storage" else cdt
        Mvec = None if mfac is None else mfac(values.astype(mdt), fmv_low)
        X, iters, resid2, conv, outer = mixed_mod.ir_loop(
            mv_wide, fmv_low, rhs, x0, tols, maxiter, cti,
            inner_iters, max_outer, eta, cdt, Mvec=Mvec, solver=solver,
        )
        return X.astype(req_dt), iters, resid2, conv, outer

    return run


class _InFlight:
    """One dispatched-but-not-retired bucket program: everything
    ``_retire`` needs to scatter results, account phases and decide
    requeues once the device finishes. Never holds the (possibly
    donated) input arrays — only the program outputs."""

    __slots__ = ("reqs", "dt", "solver", "allow_requeue", "plan", "key",
                 "bkt", "nb", "out", "built", "snap", "t0", "t_packed",
                 "t_solve0", "t_dispatched", "sampled", "policy", "auto",
                 "_ready")

    def __init__(self, reqs, dt, solver, allow_requeue, plan, key, bkt,
                 nb, out, built, snap, t0, t_packed, t_solve0,
                 t_dispatched, sampled, policy=mixed_mod.EXACT, auto=None):
        self.reqs, self.dt, self.solver = reqs, dt, solver
        self.allow_requeue, self.plan, self.key = allow_requeue, plan, key
        self.bkt, self.nb, self.out = bkt, nb, out
        self.built, self.snap = built, snap
        self.t0, self.t_packed, self.t_solve0 = t0, t_packed, t_solve0
        self.t_dispatched, self.sampled = t_dispatched, sampled
        # the resolved dtype policy this bucket ran under (ISSUE 15):
        # 'exact' or a reduced policy — the promote rung keys off it
        self.policy = policy
        # the autopilot observation token (ISSUE 16): retire settles the
        # dispatch's measured score against it; None when the tuner is
        # off or this dispatch bypassed it (requeues, explicit overrides)
        self.auto = auto
        self._ready = False

    def is_ready(self) -> bool:
        """Non-blocking: True when every device output has
        materialized (host-returning programs — gmres/row — are ready
        by construction). Latches once True — readiness never
        regresses, so repeat polls are one attribute read."""
        if self._ready:
            return True
        try:
            ok = all(
                l.is_ready() for l in jax.tree_util.tree_leaves(self.out)
                if hasattr(l, "is_ready")
            )
        except Exception:  # noqa: BLE001 - treat odd leaves as ready
            ok = True
        self._ready = ok
        return ok


class _WarmReplay:
    """Background vault warm-start replay (ISSUE 13): ``_prebuild``
    warm replay runs on this daemon thread so construction returns
    immediately and the first requests after a restart aren't blocked
    behind AOT compiles. The dispatch path coordinates through
    :meth:`wait_for`: a program the manifest plans to replay is waited
    on (bounded) instead of rebuilt, so the serving window stays at
    zero plan-cache misses even when traffic races the replay — the
    chaos scenario-10 contract."""

    def __init__(self, session, planned):
        self._planned = frozenset(planned)
        self._cond = threading.Condition()
        self._done: set = set()
        self._finished = False
        self.count = 0
        self._thread = threading.Thread(
            target=self._run, args=(session,),
            name="sparse-tpu-warm-replay", daemon=True,
        )
        self._thread.start()

    @property
    def active(self) -> bool:
        return not self._finished

    def _run(self, session) -> None:
        try:
            self.count = session._replay_manifest(notify=self._mark)
        except Exception:  # noqa: BLE001 - replay is never a liability
            pass
        finally:
            with self._cond:
                self._finished = True
                self._cond.notify_all()

    def _mark(self, key: str) -> None:
        with self._cond:
            self._done.add(key)
            self._cond.notify_all()

    def wait_for(self, key: str, timeout: float = 120.0) -> None:
        """Block while ``key`` is planned but not yet replayed (bounded;
        a dead/stuck replay degrades to an ordinary build)."""
        if key not in self._planned:
            return
        deadline = time.monotonic() + float(timeout)
        with self._cond:
            while not self._finished and key not in self._done:
                left = deadline - time.monotonic()
                if left <= 0:
                    return
                self._cond.wait(min(left, 0.25))

    def join(self, timeout: float | None = None) -> None:
        self._thread.join(timeout)


class SolveSession:
    """Queue -> coalesce -> bucket -> dispatch -> scatter.

    Parameters
    ----------
    solver : 'cg' | 'bicgstab' | 'gmres'
    batch_max : max lanes per dispatched batch (default
        ``settings.batch_max``)
    bucket_policy : 'pow2' | 'exact' (default ``settings.batch_bucket``)
    conv_test_iters : convergence-test cadence of the masked loops
    restart : GMRES restart length (gmres only)
    auto_flush : when set, ``submit`` flushes as soon as a pattern has
        this many queued requests (a latency/throughput knob; None =
        explicit ``flush()`` only). With a pipelined session
        (``inflight > 1``) this is the streaming fast path: the
        auto-flush dispatches WITHOUT waiting (``flush(wait=False)``),
        so ``submit`` never blocks on a solve
    inflight : the streaming-dispatch window (ISSUE 13,
        docs/batching.md "Streaming dispatch"): max bucket programs in
        flight on the device before dispatch retires (blocks on) the
        oldest. 1 = the classic synchronous path, bit-identical
        dispatch/retire interleaving; 2 (the ``SPARSE_TPU_INFLIGHT``
        default) double-buffers — the host packs/uploads bucket N+1
        while the device solves bucket N. Compiled programs are
        identical at every setting. Default ``None`` =
        ``settings.inflight``
    max_queue_depth : admission-control threshold (ISSUE 13): max
        tickets submitted-but-unfinalized (queued + in flight) before
        ``submit`` applies backpressure — ``admission='block'`` drives
        the pipeline (retire/dispatch) until below the threshold,
        ``'reject'`` raises :class:`AdmissionError`; both emit a
        ``batch.admission`` event and count into the always-on
        ``batch.admissions{mode}`` counter. None (default) = unbounded
    admission : 'block' | 'reject' — what ``submit`` does at
        ``max_queue_depth`` (ignored when that is None)
    requeue : requeue unconverged/nonfinite lanes once into a fallback
        bucket (``fallback_solver`` at a promoted dtype); on by default
    fallback_solver : solver of the requeue bucket (default 'gmres' —
        the most breakdown-tolerant of the three)
    dispatch_attempts : tries per bucket before its tickets fail (>= 1;
        retries cover transient dispatch faults, e.g. injected drops)
    slo_ms : the session's end-to-end latency objective per ticket
        (submit -> resolved, milliseconds). Purely observational: a
        ticket over the target still returns normally, but counts into
        ``batch.slo_misses`` and its ``batch.ticket`` terminal event is
        flagged ``slo_miss`` (None = no objective, nothing counted)
    warm_start : replay the vault's warm-start manifest on construction
        (ISSUE 9, docs/performance.md): hot (pattern, solver, bucket,
        dtype) programs from previous processes re-load their pattern
        packs from the disk tier and re-build/compile ahead of traffic,
        so serving-path dispatches start at zero plan-cache misses.
        Default ``None`` = replay iff the vault is enabled
        (``SPARSE_TPU_VAULT``); ``False`` always skips. Replay is
        best-effort — a corrupt manifest or artifact degrades to an
        ordinary cold start, never a construction failure.
    warm_async : run the warm replay on a background thread (the
        default; ISSUE 13) so construction returns immediately and
        first requests aren't blocked behind AOT compiles — dispatches
        of a program the replay is still building wait for it instead
        of rebuilding (zero serving-path builds, chaos scenario 10).
        ``False`` replays synchronously during construction (the
        pre-pipeline behavior; bench's ``cold_start`` row uses it so
        ``replay_s`` keeps measuring the replay itself). Reading
        ``warm_replayed`` joins the thread.
    profile_every : sampled timed-dispatch device profiling (ISSUE 12):
        every Nth dispatched bucket splits its solve wall clock into
        host (async dispatch) vs device (``block_until_ready``) time,
        feeding the always-on ``batch.program_device_ms{program}``
        histogram, the cost table's measured columns and the
        ``batch.dispatch`` event's ``device_ms``/``host_ms`` fields.
        Default ``None`` = ``settings.profile_every``
        (``SPARSE_TPU_PROFILE_EVERY``); 0 = off — no extra timestamps,
        identical compiled programs either way.
    """

    def __init__(self, solver: str = "cg", batch_max: int | None = None,
                 bucket_policy: str | None = None, conv_test_iters: int = 25,
                 restart: int | None = None, auto_flush: int | None = None,
                 requeue: bool = True, fallback_solver: str = "gmres",
                 dispatch_attempts: int = 2, slo_ms: float | None = None,
                 warm_start: bool | None = None, fleet=None,
                 fleet_mesh=None, fleet_min_b: int | None = None,
                 row_shard_min_n: int | None = None,
                 profile_every: int | None = None,
                 inflight: int | None = None,
                 max_queue_depth: int | None = None,
                 admission: str = "block",
                 warm_async: bool = True,
                 precond=None,
                 row_precond=None,
                 dtype_policy=None,
                 precond_dtype=None,
                 autopilot=None):
        if solver not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}")
        if fallback_solver not in _SOLVERS:
            raise ValueError(f"fallback_solver must be one of {_SOLVERS}")
        if admission not in ("block", "reject"):
            raise ValueError("admission must be 'block' or 'reject'")
        self.solver = solver
        self.batch_max = int(batch_max or settings.batch_max)
        self.bucket_policy = bucket_policy or settings.batch_bucket
        self.conv_test_iters = int(conv_test_iters)
        self.restart = restart
        self.auto_flush = auto_flush
        self.requeue = bool(requeue)
        self.fallback_solver = fallback_solver
        self.dispatch_attempts = max(int(dispatch_attempts), 1)
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        # sampled timed-dispatch device profiling (ISSUE 12): every Nth
        # dispatch splits solve wall clock at the dispatch-return
        # boundary into host vs device time (telemetry/_profiler.py).
        # 0 (the default env) = off: no extra timestamps, no extra
        # event fields, and the compiled programs are identical either
        # way — sampling never enters a trace.
        self.profile_every = (
            settings.profile_every if profile_every is None
            else max(int(profile_every), 0)
        )
        self._dispatch_seq = 0
        # streaming-dispatch pipeline (ISSUE 13): the bounded in-flight
        # window of dispatched-but-not-retired bucket programs, FIFO —
        # retirement order is dispatch order, so phase accounting and
        # the requeue path see the same sequencing as the classic
        # synchronous session
        self.inflight = max(
            int(inflight if inflight is not None else settings.inflight), 1
        )
        self.max_queue_depth = (
            None if max_queue_depth is None else max(int(max_queue_depth), 1)
        )
        self.admission = admission
        self._inflight: "collections.deque[_InFlight]" = collections.deque()
        # tickets submitted and not yet finalized (the session's share
        # of the batch.queue_depth gauge; the drift assertion in
        # session_stats checks it against pending + in-flight lanes)
        self._unfinalized = 0
        # programs built ON the serving path (a dispatch's plan-cache
        # miss, warm-replay builds excluded) — chaos scenario 10's
        # zero-serving-builds evidence
        self._serving_builds = 0
        # mesh-sharded serving tier (ISSUE 10, docs/batching.md): the
        # per-(pattern, bucket) strategy policy. `fleet` may be a mode
        # string ('auto'/'batch'/'row'), True/False, a ready FleetPolicy,
        # or None = settings.fleet (SPARSE_TPU_FLEET). Off (the default
        # env) leaves every code path byte-identical to the classic
        # single-device session.
        self.fleet = fleet_mod.FleetPolicy.resolve(
            fleet, mesh=fleet_mesh, min_b=fleet_min_b,
            row_min_n=row_shard_min_n,
        )
        # elastic topology monitor (ISSUE 20, docs/resilience.md
        # "Elastic topology"): fleet sessions only, SPARSE_TPU_REMESH=0
        # opts out. With no mesh fault active the monitor resolves the
        # construction-time mesh — clean traffic never sees a change,
        # so the default path stays byte-identical (pinned by
        # tests/test_elastic.py).
        self._elastic = (
            elastic_mod.MeshMonitor(self.fleet.mesh)
            if (settings.remesh and self.fleet.mode
                and self.fleet.mesh is not None)
            else None
        )
        # reentrancy guard for the remesh transition: quiescing retires
        # buckets whose requeues re-enter _launch's detection gate
        self._remeshing = False
        # batched preconditioner policy (ISSUE 14, docs/preconditioners
        # .md): resolves SPARSE_TPU_PRECOND / precond= / per-ticket
        # overrides into a per-(pattern, solver, bucket, dtype) choice
        # that joins the program key and the vault manifest. Off (the
        # default env) leaves keys and jaxprs byte-identical.
        self.precond = precond_mod.PrecondPolicy.resolve(precond)
        # mixed-precision serving policy (ISSUE 15, docs/performance.md
        # "Mixed precision"): resolves SPARSE_TPU_DTYPE / dtype_policy=
        # / per-ticket overrides into a per-(pattern, solver, bucket,
        # dtype) precision choice that joins the program key (.P suffix)
        # and the vault manifest. 'exact' (the default env) leaves keys,
        # jaxprs and numerics byte-identical.
        self.dtype_policy = mixed_mod.DtypePolicy.resolve(dtype_policy)
        # precond storage dtype (ISSUE 16): 'compute' (the default env)
        # factorizes/stores M at the inner sweep's compute dtype —
        # byte-identical keys and jaxprs; 'storage' is the compounding
        # arm (reduced-width factors, wide accumulation, '.W' key
        # suffix). A typo'd env raises HERE, not mid-dispatch.
        self.precond_dtype = precond_mod.canonical_precond_dtype(
            settings.precond_dtype if precond_dtype is None
            else precond_dtype
        )
        # online policy tuner (ISSUE 16, docs/autopilot.md): None when
        # off (the default env) — the session then carries no tuner
        # object and every dispatch path is byte-identical to
        # pre-autopilot behavior. `autopilot` may be a ready Autopilot,
        # True/a mode string, False, or None = SPARSE_TPU_AUTOPILOT.
        self.autopilot = autopilot_mod.Autopilot.resolve(autopilot)
        # optional row-shard-lane preconditioner hook: a callable
        # ``make_M(DistCSR) -> padded M`` (e.g. a multigrid V-cycle via
        # parallel.multigrid.vcycle_operator) threaded into
        # fleet.build_row_program
        self.row_precond = row_precond
        # per-device real-lane occupancy of the most recent dispatch
        # (the /session device dimension; also on the always-on
        # fleet.device_occupancy gauge family)
        self._device_occ: list = []
        self._patterns: dict = {}  # fingerprint -> SparsityPattern (dedupe)
        self._pending: dict = {}  # id(pattern) -> [Request]
        self.dispatches = 0
        # terminal-state tallies for the /session serving endpoint
        self._ticket_counts = {"done": 0, "failed": 0, "slo_miss": 0}
        _SESSIONS.add(self)
        # continuous-telemetry history (Axon v7): auto-start the metrics
        # sampler when SPARSE_TPU_HISTORY is set — a single attribute
        # check when off, so the disabled serving path stays
        # byte-identical (pinned by tests/test_history.py)
        _history.maybe_start()
        # serving-path persistent XLA compile cache (ISSUE 9 satellite):
        # env-gated so bucket-program executables survive restarts
        # alongside the vault's packed artifacts
        if settings.compile_cache:
            from ..utils import enable_compilation_cache

            enable_compilation_cache(settings.compile_cache)
        self._warm: _WarmReplay | None = None
        self._warm_replayed = 0
        # background ingest onboarder (ISSUE 18): created lazily on the
        # first ingest() call — a session that never ingests carries no
        # worker thread and no queue
        self._onboarder = None
        from .. import vault

        if (vault.enabled() if warm_start is None else warm_start):
            if vault.enabled():
                if warm_async:
                    try:
                        entries = vault.manifest_entries()
                    except Exception:  # noqa: BLE001 - corrupt manifest
                        entries = []
                    planned = set()
                    for e in entries:
                        key = self._manifest_plan(e)[0]
                        if key:
                            planned.add(key)
                    self._warm = _WarmReplay(self, planned)
                else:
                    self._warm_replayed = self._replay_manifest()

    @property
    def warm_replayed(self) -> int:
        """Programs the vault warm replay rebuilt. With the async
        replay (``warm_async=True``) reading this JOINS the background
        thread — it is the synchronization point for callers that need
        the replay finished (tests, the chaos drills' serving-window
        snapshots)."""
        if self._warm is not None:
            self._warm.join()
            self._warm_replayed = self._warm.count
            self._warm = None
        return self._warm_replayed

    # -- intake ------------------------------------------------------------
    def pattern_of(self, A) -> SparsityPattern:
        """Session-deduped pattern for ``A``: same structure => same
        object => same plan-cache entries across callers."""
        p = SparsityPattern.from_csr(A)
        return self._patterns.setdefault(p.fingerprint, p)

    def ingest(self, source, *, bucket: int = 1, dtype=np.float64,
               num_shards: int | None = None, tenant: str | None = None,
               wait: bool = False, timeout: float | None = None):
        """Queue one arriving matrix for background onboarding
        (ISSUE 18): parse -> fingerprint dedup -> sharded samplesort
        COO->CSR -> SELL pack + bucket prebuild + vault persistence,
        all on the bounded onboarder worker, never on the serving path.

        ``source`` is a MatrixMarket path, anything COO/CSR-shaped, or
        a raw ``(rows, cols, vals, shape)`` tuple. Returns an
        :class:`~sparse_tpu.ingest.IngestTicket` immediately (admission
        permitting — at ``SPARSE_TPU_INGEST_DEPTH`` queued arrivals the
        configured admission mode blocks or rejects); ``wait=True``
        blocks for the outcome first. ``bucket``/``dtype`` shape the
        program a cold pattern gets prebuilt ahead of its first solve.
        A dedup hit rides the existing pattern object: its first solve
        is a pure plan-cache hit — zero new compiles. ``tenant``
        attributes the onboarding in the v7 ``usage.*`` metering."""
        from ..ingest.onboard import Onboarder

        ob = self._onboarder
        if ob is None:
            ob = self._onboarder = Onboarder(self)
        t = ob.submit(
            source, bucket=bucket, dtype=dtype, num_shards=num_shards,
            tenant=tenant,
        )
        if wait:
            t.result(timeout=timeout)
        return t

    def submit(self, A, b, tol: float = 1e-8, x0=None, maxiter=None,
               pattern: SparsityPattern | None = None,
               deadline_s: float | None = None,
               tenant: str | None = None,
               precond: str | None = None,
               dtype_policy: str | None = None,
               precond_dtype: str | None = None) -> SolveTicket:
        """Queue one system. ``A`` is a CSR-shaped matrix (csr_array /
        scipy) or, with ``pattern=`` given, a bare ``(nnz,)`` value
        vector over that pattern. ``deadline_s`` is a per-ticket wall
        budget measured from submission: a ticket still queued when its
        deadline passes fails with :class:`TicketDeadlineError` instead
        of dispatching stale work. ``tenant`` stamps an optional caller
        label onto the ticket, its ``batch.ticket`` terminal event and
        the ``batch.ticket_latency`` histogram labels (ISSUE 11: the
        fairness dimension; ``None`` keeps every existing metric series
        name unchanged) — it never enters the compiled program or its
        plan-cache key.

        ``precond`` overrides the session's preconditioner policy for
        this one request (ISSUE 14): a concrete kind ('jacobi' |
        'bjacobi' | 'ilu0' | 'ic0' | 'cheby' | 'neumann'), 'auto', or
        'off'. Requests with different overrides never share a bucket
        (the override joins the flush group key, like the dtype), and
        the resolved kind joins the bucket program's plan-cache key.

        ``dtype_policy`` overrides the session's mixed-precision policy
        for this one request (ISSUE 15): 'exact', 'auto', 'f32ir' or
        'bf16ir' — same grouping/keying contract as ``precond`` (the
        resolved policy joins the program key as a ``.P`` suffix;
        'exact' keeps the historic key).

        ``precond_dtype`` overrides the session's preconditioner
        storage dtype for this one request (ISSUE 16): 'compute' or
        'storage' — same grouping/keying contract again ('storage'
        joins the key as a ``.W`` suffix; it only takes effect on a
        reduced-precision bucket with a Jacobi/ILU preconditioner and
        degrades to 'compute' with a breadcrumb elsewhere).

        With ``max_queue_depth`` set, admission control runs first
        (after validation): at the bound, ``admission='block'`` drives
        the pipeline until below it, ``'reject'`` raises
        :class:`AdmissionError` — the request is never enqueued."""
        if pattern is None:
            pattern = self.pattern_of(A)
            values = np.asarray(A.data if hasattr(A, "data") else A)
        else:
            pattern = self._patterns.setdefault(
                pattern.fingerprint, pattern
            )
            values = np.asarray(A)
        if values.shape != (pattern.nnz,):
            raise ValueError(
                f"values shape {values.shape} != (nnz={pattern.nnz},)"
            )
        b = np.asarray(b)
        if b.shape != (pattern.shape[0],):
            raise ValueError(
                f"rhs shape {b.shape} != ({pattern.shape[0]},)"
            )
        if precond is not None:
            precond = precond_mod.canonical_kind(precond)  # validate early
        if dtype_policy is not None:
            dtype_policy = mixed_mod.canonical_policy(dtype_policy)
        if precond_dtype is not None:
            precond_dtype = precond_mod.canonical_precond_dtype(
                precond_dtype
            )
        if self.max_queue_depth is not None:
            self._admit()
        t = SolveTicket(self, deadline_s=deadline_s, tenant=tenant)
        q = self._pending.setdefault(id(pattern), [])
        q.append(_Request(pattern, values, b, float(tol), x0, maxiter, t,
                          precond=precond, dtype_policy=dtype_policy,
                          precond_dtype=precond_dtype))
        _QUEUE_DEPTH.inc()
        self._unfinalized += 1
        if self.auto_flush is not None and len(q) >= self.auto_flush:
            # the streaming fast path: a pipelined session auto-flushes
            # without waiting, so submit never blocks behind a solve
            self.flush(wait=self.inflight <= 1)
        return t

    def _admit(self) -> None:
        """Admission control (ISSUE 13): backpressure at ``submit``
        keyed off the queue-depth accounting. 'reject' raises
        :class:`AdmissionError`; 'block' drives the pipeline (retire
        in-flight buckets, dispatch queued work) until the depth drops
        below ``max_queue_depth``. Both emit one ``batch.admission``
        event and count into ``batch.admissions{mode}``."""
        cap = self.max_queue_depth
        depth = self._unfinalized
        if depth < cap:
            return
        _metrics.counter(
            "batch.admissions", mode=self.admission,
            help="submit-time admission-control engagements "
            "(max_queue_depth reached), by mode",
        ).inc()
        if self.admission == "reject":
            if telemetry.enabled():
                telemetry.record(
                    "batch.admission", mode="reject", depth=depth,
                    max_queue_depth=cap,
                )
            raise AdmissionError(
                f"queue depth {depth} at max_queue_depth={cap} "
                "(admission='reject')"
            )
        t0 = time.monotonic()
        while self._unfinalized >= cap:
            if self._inflight:
                self._retire(self._inflight.popleft())
            elif self.pending:
                self._flush_pending()
            else:
                break  # nothing left to drive; never deadlock submit
        if telemetry.enabled():
            telemetry.record(
                "batch.admission", mode="block", depth=depth,
                max_queue_depth=cap,
                waited_ms=round((time.monotonic() - t0) * 1e3, 3),
            )

    @property
    def pending(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def session_stats(self) -> dict:
        """JSON-friendly live view of this session (the ``/session``
        exporter endpoint aggregates these across live sessions).

        ``mesh`` is the active serving-mesh shape (ISSUE 10 satellite:
        the stats used to have no device dimension at all) and
        ``device_occupancy`` the per-device real-lane occupancy of the
        most recent dispatch — ``[real/slot]`` per device for sharded
        buckets, a single entry for the single-device path.

        ``pipeline`` is the streaming-dispatch view (ISSUE 13): window
        capacity/depth, admission knobs, serving-path builds and the
        async-replay state. ``tickets.queue_depth_drift`` is the gauge
        drift assertion — tickets this session counted into
        ``batch.queue_depth`` minus what it can actually account for
        (queued + in flight); anything but 0 means a finalize was
        missed or double-counted (pinned at 0 by the pipeline tests)."""
        inflight_lanes = sum(f.nb for f in self._inflight)
        return {
            "solver": self.solver,
            "fallback_solver": self.fallback_solver,
            "batch_max": self.batch_max,
            "bucket_policy": self.bucket_policy,
            "slo_ms": self.slo_ms,
            "patterns": len(self._patterns),
            "dispatches": self.dispatches,
            "mesh": self.fleet.describe(),
            **({"elastic": self._elastic.describe()}
               if self._elastic is not None else {}),
            "precond": self.precond.describe(),
            "dtype_policy": self.dtype_policy.describe(),
            **({"autopilot": self.autopilot.describe()}
               if self.autopilot is not None else {}),
            "device_occupancy": list(self._device_occ),
            "pipeline": {
                "inflight": self.inflight,
                "depth": len(self._inflight),
                "inflight_lanes": inflight_lanes,
                "max_queue_depth": self.max_queue_depth,
                "admission": self.admission,
                "serving_builds": self._serving_builds,
                "warm_replaying": (
                    self._warm is not None and self._warm.active
                ),
            },
            "tickets": {
                "pending": self.pending,
                "unfinalized": self._unfinalized,
                "queue_depth_drift": (
                    self._unfinalized - (self.pending + inflight_lanes)
                ),
                **self._ticket_counts,
            },
            **({"ingest": self._onboarder.stats()}
               if self._onboarder is not None else {}),
            # per-tenant usage metering rollup (Axon v7): process-wide
            # (the usage.* families are always-on and global); present
            # only once something was metered, so pre-v7 consumers of
            # this dict see no new key on idle sessions
            **({"usage": u} if (u := _budget.usage_stats()) else {}),
        }

    # -- warm restart (ISSUE 9; async since ISSUE 13) ----------------------
    def _manifest_plan(self, e: dict):
        """Parse one warm-start manifest entry into ``(program_key,
        solver, bucket, dtype, plan, precond, dtype_policy,
        precond_dtype, skip_reason)`` — the SINGLE place entry ->
        plan-cache key resolution lives, so the async replay's
        planned-key set (what ``_launch`` waits for) and the replay
        itself can never disagree. ``skip_reason`` is ``None`` for a
        replayable entry, ``'mesh'`` for a topology-mismatched fleet
        entry (clean cold start) and ``'malformed'`` otherwise.
        ``precond`` is the entry's recorded kind ('none' when absent —
        pre-precond manifests stay valid); ``dtype_policy`` the
        recorded precision policy ('exact' when absent — pre-mixed
        manifests stay valid, ISSUE 15); ``precond_dtype`` the recorded
        factor storage dtype ('compute' when absent — pre-autopilot
        manifests stay valid, ISSUE 16)."""
        _bad = (None, None, 0, None, None, precond_mod.NONE,
                mixed_mod.EXACT, "compute", "malformed")
        solver = e.get("solver")
        try:
            bkt = int(e.get("bucket", 0))
        except (TypeError, ValueError):
            bkt = 0
        dtstr = e.get("dtype", "")
        if solver not in _SOLVERS or bkt < 1 or not dtstr:
            return _bad
        try:
            mkind = precond_mod.canonical_kind(
                e.get("precond"), allow_auto=False
            )
        except ValueError:
            return _bad
        try:
            dpol = mixed_mod.canonical_policy(
                e.get("dtype_policy"), allow_auto=False
            )
        except ValueError:
            return _bad
        try:
            pdt = precond_mod.canonical_precond_dtype(
                e.get("precond_dtype")
            )
        except ValueError:
            return _bad
        # mesh-keyed entries (the fleet tier) only replay on the SAME
        # topology: a fingerprint mismatch — restart on a different pod
        # shape, fleet turned off — skips the entry for a clean cold
        # start instead of compiling a program this mesh cannot dispatch
        mesh_fp = e.get("mesh")
        if mesh_fp:
            if not (
                self.fleet.enabled
                and mesh_fp == self.fleet.fingerprint
            ):
                return (None, None, 0, None, None, precond_mod.NONE,
                        mixed_mod.EXACT, "compute", "mesh")
            plan = self.fleet.plan_for(e.get("strategy", "batch"))
        else:
            plan = fleet_mod.FleetPlan("single")
        try:
            dt = np.dtype(dtstr)
        except TypeError:
            return _bad
        key = (
            f"batch.{solver}.B{bkt}.{dt.str}{plan.key_suffix}"
            f"{precond_mod.key_suffix(mkind)}"
            f"{mixed_mod.key_suffix(dpol)}"
            f"{precond_mod.dtype_suffix(pdt)}"
        )
        return key, solver, bkt, dt, plan, mkind, dpol, pdt, None

    def _replay_manifest(self, notify=None) -> int:
        """Replay the vault's warm-start manifest: for every recorded
        hot (pattern, solver, bucket, dtype) program, load the pattern
        structure + SELL pack from the disk tier and rebuild/compile the
        bucket program ahead of traffic. Returns the number of programs
        replayed; every failure skips its entry (a warm start is an
        optimization, never a liability). ``notify`` (the async replay's
        hook) is called with each entry's program key once that entry is
        settled — replayed OR skipped — so a dispatch waiting on the
        key is unblocked either way."""
        from .. import vault

        t0 = time.monotonic()
        entries = vault.manifest_entries()
        replayed = 0
        mesh_skipped = 0
        for e in entries:
            key = None
            try:
                (key, solver, bkt, dt, plan, mkind, dpol, pdt,
                 skip) = self._manifest_plan(e)
                if skip is not None:
                    if skip == "mesh":
                        mesh_skipped += 1
                    continue
                pat = vault.load_pattern(e.get("pattern", ""))
                if pat is None:
                    continue
                pat = self._patterns.setdefault(pat.fingerprint, pat)
                pat.sell_pack()  # disk-tier hit (or rebuild + deposit)
                self._prebuild(pat, solver, bkt, dt, plan=plan,
                               precond=mkind, dtype_policy=dpol,
                               precond_dtype=pdt)
                replayed += 1
            except Exception:  # noqa: BLE001 - entry isolation
                continue
            finally:
                if notify is not None and key:
                    notify(key)
        if replayed:
            _metrics.counter("vault.replayed").inc(replayed)
        if telemetry.enabled():
            telemetry.record(
                "vault.replay", entries=len(entries), programs=replayed,
                mesh_skipped=mesh_skipped,
                wall_ms=round((time.monotonic() - t0) * 1e3, 3),
            )
        return replayed

    def _prebuild(self, pattern: SparsityPattern, solver: str, bkt: int,
                  dt, plan=None,
                  precond: str = precond_mod.NONE,
                  dtype_policy: str = mixed_mod.EXACT,
                  precond_dtype: str = "compute") -> None:
        """Build (and AOT-compile, via the usual cost attribution) one
        bucket program outside any dispatch — argument shapes/dtypes
        mirror ``_dispatch`` exactly (including the fleet strategy's
        mesh-fingerprinted key, the resolved precond suffix and the
        dtype-policy suffix), so the first real dispatch of this bucket
        is a plan-cache hit into a warm executable."""
        dt = np.dtype(dt)
        if plan is None:
            plan = fleet_mod.FleetPlan("single")
        key = (
            f"batch.{solver}.B{bkt}.{dt.str}{plan.key_suffix}"
            f"{precond_mod.key_suffix(precond)}"
            f"{mixed_mod.key_suffix(dtype_policy)}"
            f"{precond_mod.dtype_suffix(precond_dtype)}"
        )
        n = pattern.shape[0]
        # the same conversion pipeline as a real dispatch (np stacks ->
        # jnp.asarray), so trace signatures match under any x64 setting
        args = (
            jnp.asarray(np.zeros((bkt, pattern.nnz), dtype=dt)),
            jnp.asarray(np.zeros((bkt, n), dtype=dt)),
            jnp.asarray(np.zeros((bkt, n), dtype=dt)),
            jnp.asarray(np.zeros((bkt,), dtype=np.float64)),
            n * 10,
        )

        def build():
            tb = time.perf_counter()
            fn = self._build_program(pattern, bkt, dt, solver=solver,
                                     plan=plan, precond=precond,
                                     dtype_policy=dtype_policy,
                                     precond_dtype=precond_dtype)
            prog, _info = _cost.attribute(
                key, fn, args, pack_s=time.perf_counter() - tb,
                solver=solver, bucket=bkt, dtype=dt.str,
                n=n, nnz=pattern.nnz, warm_start=True,
                **({"precond": precond}
                   if precond != precond_mod.NONE else {}),
                **({"dtype_policy": dtype_policy}
                   if dtype_policy != mixed_mod.EXACT else {}),
                **({"precond_dtype": precond_dtype}
                   if precond_dtype != "compute" else {}),
            )
            return prog

        plan_cache.get(pattern, key, build)

    def solve_many(self, mats, rhs, tol: float = 1e-8, maxiter=None):
        """Convenience one-shot: submit a same-pattern stack, flush, and
        return ``(X (B, n), iters (B,), resid2 (B,))`` host arrays."""
        tickets = [
            self.submit(A, b, tol=tol, maxiter=maxiter)
            for A, b in zip(mats, rhs)
        ]
        self.flush()
        outs = [t.result() for t in tickets]
        return (
            np.stack([o[0] for o in outs]),
            np.asarray([o[1] for o in outs]),
            np.asarray([o[2] for o in outs]),
        )

    # -- dispatch ----------------------------------------------------------
    def flush(self, wait: bool = True) -> int:
        """Dispatch every queued request; returns the number of batches
        dispatched. Groups by (pattern, dtype), splits groups into
        ``batch_max``-sized chunks, pads each chunk to its bucket.

        ``wait=True`` (default, the classic contract) drains the
        pipeline before returning: every flushed ticket is terminal.
        ``wait=False`` is the streaming form (ISSUE 13): buckets
        dispatch through the bounded in-flight window and the call
        returns with up to ``inflight`` buckets still solving on the
        device — results arrive through the tickets' future API
        (``ready`` / ``result()``), ``poll()`` or ``drain()``.

        Exception-safe by contract (ISSUE 5 satellite): a bucket whose
        program raises marks only ITS tickets :class:`TicketFailedError`
        (after ``dispatch_attempts`` tries) — every other pending bucket
        still dispatches, and the session stays usable."""
        dispatched = self._flush_pending()
        if wait:
            self.drain()
        else:
            self.poll()
        return dispatched

    def poll(self) -> int:
        """Retire every in-flight bucket whose device results are
        already materialized (FIFO — a ready bucket behind a still-
        running one waits its turn, keeping retirement order equal to
        dispatch order). Never blocks; returns buckets retired."""
        n = 0
        while self._inflight and self._inflight[0].is_ready():
            self._retire(self._inflight.popleft())
            n += 1
        return n

    def drain(self) -> int:
        """Dispatch anything still queued, then retire EVERY in-flight
        bucket (blocking); on return all submitted tickets are terminal.
        Returns the number of buckets retired by this call."""
        self._flush_pending()
        n = 0
        while self._inflight or self.pending:
            if not self._inflight:
                # a remesh migration mid-drain requeued its lanes into
                # the pending queue (ISSUE 20): dispatch them on the new
                # topology so the all-terminal contract holds. Bounded —
                # migrations stop once identities match or the flap
                # guard latches.
                self._flush_pending()
                continue
            self._retire(self._inflight.popleft())
            n += 1
        return n

    def _flush_pending(self) -> int:
        """The dispatch half of ``flush``: deadline-check, group,
        chunk, and enqueue every pending request through the pipeline
        window. Terminal-by-now tickets (deadline-expired, failed
        buckets) finalize here; dispatched tickets finalize at retire."""
        dispatched = 0
        pending, self._pending = self._pending, {}
        for q in pending.values():
            # per-ticket deadlines: fail stale work instead of solving it
            live, expired = [], []
            for r in q:
                if r.ticket.expired:
                    r.ticket._fail(TicketDeadlineError(
                        f"deadline {r.ticket.deadline_s}s passed before "
                        "dispatch"
                    ))
                    _DEADLINE_FAILED.inc()
                    expired.append(r)
                else:
                    live.append(r)
            if expired and telemetry.enabled():
                telemetry.record(
                    "batch.deadline", solver=self.solver, stage="dispatch",
                    lanes=len(expired),
                    tickets=[r.ticket.id for r in expired],
                )
            for r in expired:
                self._finalize_ticket(r.ticket)
            # one group per (result dtype, precond override, dtype-policy
            # override, precond-dtype override) so stacked values are
            # homogeneous and every lane of a bucket shares one
            # preconditioner + precision + factor-storage choice
            by_dt: dict = {}
            for r in live:
                dt = np.result_type(r.values.dtype, r.b.dtype)
                by_dt.setdefault(
                    (np.dtype(dt), r.precond or "", r.dtype_policy or "",
                     r.precond_dtype or ""),
                    [],
                ).append(r)
            for (dt, pov, dpov, wov), reqs in sorted(
                by_dt.items(),
                key=lambda kv: (kv[0][0].str, kv[0][1], kv[0][2],
                                kv[0][3]),
            ):
                for lo in range(0, len(reqs), self.batch_max):
                    chunk = reqs[lo:lo + self.batch_max]
                    err = None
                    for _attempt in range(self.dispatch_attempts):
                        try:
                            self._dispatch(chunk, dt, precond=pov or None,
                                           dtype_policy=dpov or None,
                                           precond_dtype=wov or None)
                            dispatched += 1
                            err = None
                            break
                        except Exception as e:  # noqa: BLE001 - contract
                            err = e
                            if not isinstance(e, InjectedDispatchFailure):
                                break  # real failures don't auto-retry
                    if err is not None:
                        _BUCKET_FAILURES.inc()
                        for r in chunk:
                            r.ticket._fail(err)
                            self._finalize_ticket(r.ticket)
        return dispatched

    # -- deferred readback (the ticket future API's engine) ----------------
    def _queued(self, t: SolveTicket) -> bool:
        return any(
            r.ticket is t for q in self._pending.values() for r in q
        )

    def _find_inflight(self, t: SolveTicket):
        for fl in self._inflight:
            if any(r.ticket is t for r in fl.reqs):
                return fl
        return None

    def _holds(self, t: SolveTicket) -> bool:
        return self._queued(t) or self._find_inflight(t) is not None

    def _ticket_ready(self, t: SolveTicket) -> bool:
        fl = self._find_inflight(t)
        return fl is not None and fl.is_ready()

    def _retire_through(self, fl) -> None:
        """Retire FIFO from the window head up to and including ``fl``."""
        while self._inflight:
            head = self._inflight.popleft()
            self._retire(head)
            if head is fl:
                return

    def _resolve_ticket(self, t: SolveTicket,
                        timeout: float | None = None) -> None:
        """Drive the pipeline until ``t`` is terminal — dispatch it if
        still queued, retire its bucket (and everything ahead) if in
        flight, follow it through a requeue. With a timeout, poll
        readiness instead of blocking and return (ticket still PENDING)
        once the budget lapses."""
        deadline = (
            None if timeout is None
            else time.monotonic() + max(float(timeout), 0.0)
        )
        if t.state is TicketState.PENDING and self._pending:
            # the legacy result() contract: a pending ticket flushes the
            # session (every queued pattern), just without blocking —
            # the retire loop below does exactly the waiting needed
            self._flush_pending()
        while t.state is TicketState.PENDING:
            fl = self._find_inflight(t)
            if fl is None:
                return  # unresolved/failed: result() raises
            if deadline is None:
                self._retire_through(fl)
            elif fl.is_ready():
                self._retire_through(fl)
            elif time.monotonic() >= deadline:
                return
            else:
                time.sleep(2e-4)

    def _finalize_ticket(self, t: SolveTicket) -> None:
        """Terminal accounting for one resolved ticket: end-to-end
        latency into the always-on ``batch.ticket_latency`` histogram
        (labeled by the solver that produced the result), SLO-miss
        counting against the session target, and — telemetry on — the
        ``batch.ticket`` terminal event closing the ticket's trace.

        Also the queue-depth accounting point (ISSUE 13 satellite): the
        ``batch.queue_depth`` gauge decrements HERE, once per ticket —
        never in bulk up front — so an exception mid-flush or a
        deadline-expired lane can no longer leave the gauge out of sync
        with reality (``session_stats()['tickets']['queue_depth_drift']``
        is the assertion)."""
        if t.t_done is not None:
            return  # already finalized (a requeue resolves in-flush)
        t.t_done = time.monotonic()
        _QUEUE_DEPTH.dec()
        self._unfinalized -= 1
        latency_s = t.t_done - t.t_submit
        solver = t.solver or self.solver
        # tenant-labeled series only exist for tenant-tagged tickets:
        # the default (None) keeps the pre-existing {solver} series names
        labels = {"solver": solver}
        if t.tenant is not None:
            labels["tenant"] = t.tenant
        if t.dtype_policy is not None:
            # reduced-precision lanes only (ISSUE 15): the default
            # 'exact' path keeps the pre-existing series names
            labels["dtype_policy"] = t.dtype_policy
        _metrics.histogram(
            "batch.ticket_latency", help=_TICKET_LATENCY_HELP,
            **labels,
        ).observe(latency_s)
        slo_miss = self.slo_ms is not None and latency_s * 1e3 > self.slo_ms
        if slo_miss:
            _SLO_MISSES.inc()
            self._ticket_counts["slo_miss"] += 1
        state = "done" if t.done else "failed"
        self._ticket_counts[state] += 1
        # per-tenant usage metering (Axon v7): solve counts and SLO
        # misses attributed to the ticket's tenant label ('-' for
        # untagged tickets). Always-on — these are the denominators/
        # numerators the budget engine's per-tenant burn rates read.
        tenant = t.tenant or "-"
        _metrics.counter(
            "usage.tickets",
            help="resolved tickets per tenant (the usage metering and "
            "per-tenant burn-rate denominator)",
            tenant=tenant, state=state,
        ).inc()
        if slo_miss:
            _metrics.counter(
                "usage.slo_misses",
                help="SLO-missing tickets per tenant",
                tenant=tenant,
            ).inc()
        if telemetry.enabled():
            fields = {
                "ticket": t.id,
                "state": state,
                "solver": solver,
                "latency_ms": round(latency_s * 1e3, 3),
                "requeued": t.requeued,
            }
            if t.tenant is not None:
                fields["tenant"] = t.tenant
            if t.dtype_policy is not None:
                fields["dtype_policy"] = t.dtype_policy
                fields["promoted"] = t.promoted
            if t.phase_ms:
                fields["phases"] = {
                    k: round(v, 3) for k, v in t.phase_ms.items()
                }
            if t.done:
                fields["converged"] = bool(t._out[3])
            if isinstance(t.error, TicketDeadlineError):
                fields["reason"] = "deadline"
            elif t.error is not None:
                fields["reason"] = repr(t.error)[:200]
            if self.slo_ms is not None:
                fields["slo_ms"] = self.slo_ms
                fields["slo_miss"] = slo_miss
            telemetry.record("batch.ticket", **fields)

    def _fleet_account(self, plan, solver, dt, nb, bkt, iters,
                       solve_s, policy=mixed_mod.EXACT,
                       tenants=None) -> None:
        """Post-dispatch fleet accounting (ISSUE 10): per-device lane
        occupancy (session stats + always-on gauges), the batch-sharded
        program's measured-collective commit (the per-iteration
        all-converged psum the shard_map trace noted), and — telemetry
        on — the ``fleet.dispatch``/``fleet.shard`` events plus the
        ``comm.measured`` reconciliation against the analytic model."""
        S = plan.S
        if plan.strategy == "row":
            # a row-sharded system spans EVERY device (row blocks), so
            # each one is fully occupied by the single lane
            occ = [1] * S
            per = 1
        else:
            occ = fleet_mod.device_lane_counts(nb, bkt, S)
            per = max(bkt // max(S, 1), 1)
        self._device_occ = [round(c / per, 4) for c in occ]
        for d, c in enumerate(occ):
            _metrics.gauge(
                "fleet.device_occupancy", device=str(d),
                help="real lanes / bucket slots on this device in the "
                "most recent dispatched bucket",
            ).set(c / per)
        if not plan.sharded:
            return
        led = None
        execs = 0
        if (
            plan.strategy == "batch" and solver != "gmres"
            and policy == mixed_mod.EXACT
        ):
            # reduced-precision programs run psums in BOTH loop levels
            # (outer sweeps + inner sweeps), so the iters-based execution
            # count below would under-account them — their ledgers stay
            # uncommitted rather than committing wrong bytes
            # the while-condition psum ran (global iterations + 1)
            # times; global iterations == the slowest lane's freeze
            # step (pad lanes freeze at the first test point, so the
            # max over ALL bkt lanes is exact)
            led = fleet_mod.batch_ledger(plan.fingerprint, solver, bkt, dt)
            execs = int(np.asarray(iters).max(initial=0)) + 1
            if led.entries:
                led.commit(execs, S)
                # per-tenant usage metering (Axon v7): the dispatch's
                # modeled collective volume split evenly across its
                # real lanes and attributed per tenant
                if tenants:
                    share = fleet_mod.batch_comm_model_bytes(
                        S, execs - 1
                    ) / len(tenants)
                    for tn in tenants:
                        _metrics.counter(
                            "usage.collective_bytes",
                            help="modeled collective bytes attributed "
                            "per tenant (even split across each "
                            "sharded dispatch's real lanes)",
                            tenant=tn or "-",
                        ).add(share)
        if not telemetry.enabled():
            return
        telemetry.record(
            "fleet.dispatch", strategy=plan.strategy, S=S, bucket=bkt,
            lanes=nb, solver=solver, mesh=plan.fingerprint,
            device_lanes=occ,
        )
        for d, c in enumerate(occ):
            telemetry.record(
                "fleet.shard", device=d, lanes=c, bucket_lanes=per,
                strategy=plan.strategy,
            )
        if led is not None and led.entries and execs > 1:
            _comm.record_measured(
                "fleet.batch", led, executions=execs, shards=S,
                model_bytes=fleet_mod.batch_comm_model_bytes(S, execs - 1),
                solve_s=solve_s, strategy=plan.strategy, bucket=bkt,
                solver=solver,
            )

    def _dispatch(self, reqs, dt, solver: str | None = None,
                  allow_requeue: bool = True,
                  precond: str | None = None,
                  dtype_policy: str | None = None,
                  precond_dtype: str | None = None) -> None:
        """Enqueue one bucket through the streaming pipeline: launch
        (pack -> upload -> async program call) under the lanes' ticket
        scope, admit the dispatch to the bounded in-flight window, and
        retire the oldest dispatch(es) once the window is full —
        ``inflight=1`` therefore retires immediately (the classic
        synchronous interleaving, bit-identical by test)."""
        # every event this dispatch causes — batch.*, kernel.failover,
        # fault.injected, plan_cache.compile — carries the lanes' ticket
        # ids (replace semantics: a requeue re-enters with its own lanes)
        with telemetry.ticket_scope(*(r.ticket.id for r in reqs)):
            fl = self._launch(reqs, dt, solver, allow_requeue, precond,
                              dtype_policy, precond_dtype)
        if fl is None:
            return  # degraded at launch; lanes already resolved
        self._inflight.append(fl)
        depth = len(self._inflight)
        _INFLIGHT.set(depth)
        if telemetry.enabled():
            telemetry.record(
                "batch.inflight", depth=depth, capacity=self.inflight,
                program=fl.key, lanes=fl.nb,
            )
        while len(self._inflight) >= self.inflight:
            self._retire(self._inflight.popleft())

    def _launch(self, reqs, dt, solver: str | None,
                allow_requeue: bool, precond: str | None = None,
                dtype_policy: str | None = None,
                precond_dtype: str | None = None):
        """The host half of a dispatch: pack the lane stacks, stage the
        upload (``bucket.stage_lanes`` — pad + eager ``device_put``),
        resolve the bucket program (waiting for an in-progress warm
        replay of the same program instead of rebuilding it), and call
        it WITHOUT blocking. Returns the :class:`_InFlight` record, or
        ``None`` when the compiled path was unavailable and the lanes
        were already resolved on the eager degraded path."""
        t0 = time.monotonic()
        if _faults.ACTIVE:
            # elastic detection, forged-world trigger (ISSUE 20): a live
            # ``mesh`` fault clause changes what the world offers —
            # checked BEFORE the drop/delay actions so a slice loss
            # migrates this launch's lanes instead of failing them. The
            # disrupt draw is the gate: a spent clause budget detects
            # nothing (the drill then recovers via session.remesh()).
            if (
                self._elastic is not None and not self._elastic.latched
                and not self._remeshing
            ):
                tgt = self._elastic.changed(self.fleet)
                if tgt is not None and _faults.mesh_disrupt() is not None:
                    self._remesh_migrate(reqs, tgt, reason="fault")
                    return None
            for act in _faults.dispatch_actions():
                if act[0] == "drop":
                    raise InjectedDispatchFailure(
                        "injected dispatch drop (resilience.faults)"
                    )
                if act[0] == "delay":
                    time.sleep(act[1] / 1e3)
        pattern = reqs[0].pattern
        nb = len(reqs)
        # autopilot hook (ISSUE 16, docs/autopilot.md): an otherwise
        # untouched serving dispatch — no explicit solver (requeues and
        # fallbacks carry one), no per-ticket/flush-group overrides —
        # asks the tuner which policy arm to run. The arm's parts then
        # flow through the SAME resolution below as real overrides, so
        # the tuner can only pick configurations a caller could have
        # asked for. `auto` is the observation token retire settles.
        auto = None
        if (
            self.autopilot is not None and allow_requeue
            and solver is None and precond is None
            and dtype_policy is None and precond_dtype is None
        ):
            gbkt = bucketing.bucket_batch(
                nb, policy=self.bucket_policy, batch_max=self.batch_max
            )
            spec, auto = self.autopilot.assign(
                pattern, self.solver, gbkt, np.dtype(dt),
                slo_ms=self.slo_ms,
                mesh_fp=(self.fleet.fingerprint if self.fleet.enabled
                         else None),
            )
            solver = spec.get("solver")
            precond = spec.get("precond")
            dtype_policy = spec.get("dtype_policy")
            precond_dtype = spec.get("precond_dtype")
            if "inflight" in spec:
                # the pipeline-depth arm: takes effect as the admission
                # bound of this and later dispatches (a session knob,
                # not a program property — no key impact)
                self.inflight = max(int(spec["inflight"]), 1)
        solver = solver or self.solver
        # fleet strategy first, bucket second: a batch-sharded bucket
        # must round up to a mesh multiple (mesh-pad lanes carry the
        # same instant-converge contract as ordinary pad lanes and are
        # counted against the FINAL bucket below — the pad-accounting
        # bugfix), a row-sharded submission is exactly one lane
        plan = self.fleet.decide(pattern, nb, solver)
        if plan.strategy == "row":
            bkt = 1
        else:
            bkt = bucketing.bucket_batch(
                nb, policy=self.bucket_policy, batch_max=self.batch_max,
                multiple_of=(plan.S if plan.strategy == "batch" else 1),
            )
        values = np.stack([r.values.astype(dt) for r in reqs])
        rhs = np.stack([r.b.astype(dt) for r in reqs])
        tols = np.asarray([r.tol for r in reqs])
        x0 = None
        if any(r.x0 is not None for r in reqs):
            x0 = np.stack([
                np.zeros(pattern.shape[0], dt) if r.x0 is None
                else np.asarray(r.x0, dtype=dt)
                for r in reqs
            ])
        # pad + eager host->device upload: the transfers overlap the
        # solve of whatever bucket is currently in flight
        values, rhs, tols, x0, _ = bucketing.stage_lanes(
            values, rhs, tols, bkt, x0=x0
        )
        maxiter = max(
            (r.maxiter if r.maxiter is not None else pattern.shape[0] * 10)
            for r in reqs
        )
        snap = plan_cache.snapshot()
        # the resolved per-(pattern, solver, bucket, dtype) precond kind
        # (ISSUE 14): per-ticket override first, else the session
        # policy; joins the program key so 'none' keys stay historic
        mkind = self.precond.decide(
            pattern, solver, bkt, dt, override=precond
        )
        # the resolved dtype policy (ISSUE 15): override > session >
        # env, with the promote rung's pinned groups forcing 'exact'.
        # Row-sharded plans always solve exact — dist_cg has no fused
        # IR form (breadcrumbed like any other policy degradation).
        pol = self.dtype_policy.decide(
            pattern, solver, bkt, dt, override=dtype_policy
        )
        if pol != mixed_mod.EXACT and plan.strategy == "row":
            mixed_mod.DtypePolicy._fallback(pol, "row-sharded plan")
            pol = mixed_mod.EXACT
        # the resolved precond storage dtype (ISSUE 16): override >
        # session/env. 'storage' only means anything on a reduced-
        # precision bucket whose preconditioner factorizes values
        # (Jacobi/ILU families) — everywhere else it degrades to
        # 'compute' with a breadcrumb, keeping the key suffix empty.
        pdt = (
            self.precond_dtype if precond_dtype is None
            else precond_mod.canonical_precond_dtype(precond_dtype)
        )
        if pdt != "compute":
            reason = None
            if pol == mixed_mod.EXACT:
                reason = "exact dtype policy: no reduced storage width"
            elif mkind == precond_mod.NONE:
                reason = "no preconditioner"
            elif mkind in ("cheby", "neumann"):
                reason = f"{mkind} applies A itself: no stored factors"
            elif plan.strategy == "row":
                reason = "row-sharded plan"
            if reason is not None:
                if telemetry.enabled():
                    telemetry.record(
                        "coverage.fallback", op="precond.storage",
                        reason=reason, to="compute",
                    )
                pdt = "compute"
        if pol != mixed_mod.EXACT:
            # stamp the reduced policy on the lanes (sticky across a
            # later promote_dtype requeue, so the terminal event still
            # records that the ticket rode the mixed path)
            for r in reqs:
                r.ticket.dtype_policy = pol
        faulty = _faults.ACTIVE and (
            _faults.targets("matvec") or _faults.targets("precond")
        )
        key = (
            f"batch.{solver}.B{bkt}.{np.dtype(dt).str}{plan.key_suffix}"
            f"{precond_mod.key_suffix(mkind)}{mixed_mod.key_suffix(pol)}"
            f"{precond_mod.dtype_suffix(pdt)}"
        )
        if faulty:
            # fault-wrapped programs carry the injection callback in
            # their trace: never share cache entries with clean ones
            key += ".faults"
        args = (values, rhs, x0, tols, maxiter)
        t_packed = time.monotonic()
        built: dict = {}

        def build():
            # a cache miss builds AND attributes: pack/trace wall-clock,
            # AOT compile duration, XLA cost/memory analysis — one
            # plan_cache.compile event per program, ever (same cadence
            # as the miss itself)
            tb = time.perf_counter()
            fn = self._build_program(pattern, bkt, np.dtype(dt),
                                     solver=solver, plan=plan,
                                     precond=mkind, dtype_policy=pol,
                                     precond_dtype=pdt)
            prog, info = _cost.attribute(
                key, fn, args,
                pack_s=time.perf_counter() - tb,
                solver=solver, bucket=bkt, dtype=np.dtype(dt).str,
                n=pattern.shape[0], nnz=pattern.nnz,
                **({"precond": mkind}
                   if mkind != precond_mod.NONE else {}),
                **({"dtype_policy": pol}
                   if pol != mixed_mod.EXACT else {}),
                **({"precond_dtype": pdt}
                   if pdt != "compute" else {}),
            )
            built.update(info)
            return prog

        try:
            if self._warm is not None and self._warm.active:
                # the async replay may already be compiling this very
                # program: wait for it rather than building twice — the
                # zero-serving-miss warm restart contract
                self._warm.wait_for(key)
            prog = plan_cache.get(pattern, key, build)
            if built:
                self._serving_builds += 1
            if built and not faulty:
                # a freshly built bucket program is warm-start state:
                # note it (and its pattern artifact) in the vault
                # manifest so a restarted process replays it. Fault-
                # wrapped programs are never noted — their traces carry
                # the injection callback.
                from .. import vault

                if vault.enabled() and plan.strategy != "row":
                    # row programs are rebuilt per dispatch (no compiled
                    # artifact worth replaying); batch-sharded programs
                    # note the mesh fingerprint so only a same-topology
                    # restart replays them; preconditioned programs note
                    # their resolved kind (ISSUE 14) so the replay
                    # rebuilds the SAME keyed program, symbolic maps
                    # loading from their vault artifacts
                    vault.note_program(
                        pattern, solver=solver, bucket=bkt,
                        dtype=np.dtype(dt).str,
                        mesh=(plan.fingerprint if plan.sharded else None),
                        strategy=(plan.strategy if plan.sharded else None),
                        precond=(mkind if mkind != precond_mod.NONE
                                 else None),
                        dtype_policy=(pol if pol != mixed_mod.EXACT
                                      else None),
                        precond_dtype=(pdt if pdt != "compute"
                                       else None),
                    )
            # sampled timed dispatch (ISSUE 12): every Nth dispatch
            # takes ONE extra timestamp at the dispatch-return boundary
            # so the solve wall clock splits into host (async dispatch)
            # vs device (results-ready wait) time. Off (the default)
            # takes no timestamp at all; the program and its plan-cache
            # key are identical either way.
            if mkind != precond_mod.NONE and telemetry.enabled():
                # the host-side record that this dispatch's program
                # factorizes/applies M in-trace (the numeric build is
                # compiled into the bucket program)
                telemetry.record(
                    "precond.apply", precond=mkind, lanes=nb,
                    solver=solver, bucket=bkt,
                )
            self._dispatch_seq += 1
            sampled = (
                self.profile_every > 0
                and self._dispatch_seq % self.profile_every == 0
            )
            t_solve0 = time.monotonic()
            out = prog(*args)
            t_dispatched = time.monotonic() if sampled else None
        except Exception as e:  # noqa: BLE001 - degrade, don't strand
            # elastic detection, dispatch-failure trigger (ISSUE 20): a
            # classified topology error revalidates the mesh — when the
            # world really differs, migrate the lanes and re-plan
            # instead of eagerly degrading onto a dead topology
            if (
                self._elastic is not None and not self._elastic.latched
                and not self._remeshing and _faults.is_topology_error(e)
            ):
                tgt = self._elastic.changed(self.fleet)
                if tgt is not None:
                    self._remesh_migrate(
                        reqs, tgt, reason="dispatch_error"
                    )
                    return None
            self._degrade(reqs, dt, solver, nb, e)
            return None
        return _InFlight(
            reqs, dt, solver, allow_requeue, plan, key, bkt, nb, out,
            built, snap, t0, t_packed, t_solve0, t_dispatched, sampled,
            policy=pol, auto=auto,
        )

    def _degrade(self, reqs, dt, solver, nb, e) -> None:
        """Graceful degradation (ISSUE 5): the compiled batched path is
        unavailable (Pallas lowering gone mid-session, plan cache
        failure, injected program fault) — solve the lanes one by one
        on the eager path instead of failing the bucket, then finalize
        them (they never reach a retire)."""
        _DEGRADED.inc()
        if telemetry.enabled():
            telemetry.record(
                "batch.degraded", solver=solver, reason=repr(e)[:200],
                lanes=nb,
            )
        try:
            self._solve_degraded(reqs, dt, solver)
        except Exception as e2:  # noqa: BLE001 - strand nothing
            for r in reqs:
                r.ticket._fail(e2)
        for r in reqs:
            self._finalize_ticket(r.ticket)

    def _retire(self, fl: _InFlight) -> None:
        """The deferred-readback half of a dispatch: wait for the
        bucket's device results, scatter them to the tickets, decide
        requeues (deadlines re-checked HERE — a lane gone stale in
        flight never spends a requeue past its deadline), account
        phases/metrics/events and finalize every lane that isn't
        continuing into a fallback bucket. Never raises into the
        caller's flush — any failure degrades or fails this bucket's
        lanes only."""
        _INFLIGHT.set(len(self._inflight))
        with telemetry.ticket_scope(*(r.ticket.id for r in fl.reqs)):
            try:
                self._retire_scoped(fl)
            except Exception as e:  # noqa: BLE001 - bucket isolation
                for r in fl.reqs:
                    r.ticket._fail(e)
                    self._finalize_ticket(r.ticket)

    def _retire_scoped(self, fl: _InFlight) -> None:
        reqs, dt, solver, plan = fl.reqs, fl.dt, fl.solver, fl.plan
        nb, bkt, key = fl.nb, fl.bkt, fl.key
        try:
            try:
                jax.block_until_ready(fl.out)
            except Exception:
                pass  # non-jax leaves (ints) — np.asarray blocks below
            t_solved = time.monotonic()
            # IR bucket programs (ISSUE 15) return a 5th output: the
            # shared refinement-sweep count
            if len(fl.out) == 5:
                X, iters, resid2, conv, ir_outer = fl.out
                ir_outer = int(np.asarray(ir_outer))
            else:
                X, iters, resid2, conv = fl.out
                ir_outer = None
            X = np.asarray(X)
            iters = np.asarray(iters)
            resid2 = np.asarray(resid2)
            conv = np.asarray(conv)
        except Exception as e:  # noqa: BLE001 - degrade, don't strand
            self._degrade(reqs, dt, solver, nb, e)
            return
        fl.out = None  # release device buffers promptly
        if ir_outer is not None:
            _metrics.counter(
                "mixed.ir_outer_iters",
                help="iterative-refinement outer sweeps across all IR "
                "solves",
            ).inc(ir_outer)
        t_read = time.monotonic()
        profile_ms = None
        if fl.sampled:
            profile_ms = (
                max((fl.t_dispatched - fl.t_solve0) * 1e3, 0.0),  # host
                max((t_solved - fl.t_dispatched) * 1e3, 0.0),  # device
            )
            _profiler.record_device_sample(key, *profile_ms)
        requeue_lanes = []
        promote_lanes = []
        promote_nonfinite = False
        stale_lanes = []
        for i, r in enumerate(reqs):
            r.ticket._offer(X[i], iters[i], resid2[i], conv[i],
                            solver=solver)
            failed = not conv[i] or not np.isfinite(resid2[i])
            if fl.allow_requeue and self.requeue and failed and (
                not r.ticket.requeued
                or (fl.policy != mixed_mod.EXACT and not r.ticket.promoted)
            ):
                # deadline re-check at readback (ISSUE 13): the lane
                # failed AND its budget lapsed while the bucket was in
                # flight — keep the (unconverged) result it has rather
                # than spending a fallback solve past the deadline
                if deadline_remaining_s(
                    r.ticket.t_submit, r.ticket.deadline_s
                ) <= 0:
                    stale_lanes.append(r)
                    continue
                if fl.policy != mixed_mod.EXACT and not r.ticket.promoted:
                    # the promote_dtype rung (ISSUE 15): an anomalous
                    # reduced-precision lane re-solves at 'exact' FIRST
                    # — same solver, one rung AHEAD of the classic
                    # solver-escalation requeue (which stays available
                    # if the exact re-solve fails too)
                    r.ticket.promoted = True
                    if not np.isfinite(resid2[i]):
                        promote_nonfinite = True
                    promote_lanes.append(r)
                else:
                    r.ticket.requeued = True
                    requeue_lanes.append(r)
        if stale_lanes:
            _STALE_REQUEUES.inc(len(stale_lanes))
            if telemetry.enabled():
                telemetry.record(
                    "batch.deadline", solver=solver, stage="readback",
                    lanes=len(stale_lanes),
                    tickets=[r.ticket.id for r in stale_lanes],
                )
        self.dispatches += 1
        _DISPATCHES.inc()
        # occupancy/waste count against the FINAL bucket (incl. any
        # mesh-multiple rounding); pad lanes are excluded by construction
        _BUCKET_OCCUPANCY.observe(nb / bkt)
        _PAD_WASTE.inc(bkt - nb)
        # per-tenant usage metering (Axon v7): lanes dispatched and —
        # sampled dispatches — the measured device-ms split per lane,
        # attributed to each lane's tenant. Rides the existing retire
        # path: no new timestamps, no device touch.
        device_share = (
            profile_ms[1] / nb if profile_ms is not None and nb else None
        )
        for r in reqs:
            tenant = r.ticket.tenant or "-"
            _metrics.counter(
                "usage.lanes",
                help="real lanes dispatched per tenant (requeues count "
                "again — the work actually done)",
                tenant=tenant,
            ).inc()
            if device_share is not None:
                _metrics.counter(
                    "usage.device_ms",
                    help="sampled device milliseconds attributed per "
                    "tenant (even split of each sampled dispatch's "
                    "device time across its real lanes)",
                    tenant=tenant,
                ).add(device_share)
        self._fleet_account(
            plan, solver, dt, nb, bkt, iters,
            max(t_solved - fl.t_solve0, 0.0), policy=fl.policy,
            tenants=[r.ticket.tenant for r in reqs],
        )
        if fl.auto is not None and self.autopilot is not None:
            # settle the dispatch's measurement against its autopilot
            # token (ISSUE 16): the sampled device split when the
            # profiler took one, else the solve wall clock; a bucket
            # that promoted or left lanes unconverged scores as a
            # failure regardless of speed
            try:
                self.autopilot.observe(
                    fl.auto,
                    solve_ms=max((t_solved - fl.t_solve0) * 1e3, 0.0),
                    device_ms=(profile_ms[1] if profile_ms is not None
                               else None),
                    iters_mean=float(iters[:nb].mean()) if nb else 0.0,
                    lanes=nb,
                    converged=float(conv[:nb].mean()) if nb else 1.0,
                    promoted=bool(promote_lanes),
                )
            except Exception:  # noqa: BLE001 - tuning never breaks serving
                pass
        if telemetry.enabled():
            # bucket-level phase wall clocks, accumulated onto each
            # lane's ticket (a requeued lane sums both dispatches).
            # compile_ms is the build's share (pattern pack + AOT
            # compile), which ran inside plan_cache.get — i.e. between
            # t_packed and t_solve0 — so the phases stay disjoint. The
            # solve phase spans dispatch -> results ready, so with
            # streaming dispatch it absorbs any in-flight wait and the
            # phases still tile the end-to-end latency exactly.
            compile_ms = (
                fl.built.get("compile_s", 0.0)
                + fl.built.get("pack_s", 0.0)
            ) * 1e3
            pack_ms = max((fl.t_packed - fl.t0) * 1e3, 0.0)
            solve_ms = max((t_solved - fl.t_solve0) * 1e3, 0.0)
            readback_ms = max((t_read - t_solved) * 1e3, 0.0)
            for r in reqs:
                ph = r.ticket.phase_ms
                # queue wait accrues from submit (first dispatch) or
                # from the end of the previously accounted dispatch (a
                # requeue) — the phases of a requeued ticket then tile
                # its latency instead of double-counting the first pass
                base = (
                    r.ticket.t_mark if r.ticket.t_mark is not None
                    else r.ticket.t_submit
                )
                ph["queue_ms"] = ph.get("queue_ms", 0.0) + max(
                    (fl.t0 - base) * 1e3, 0.0
                )
                ph["pack_ms"] = ph.get("pack_ms", 0.0) + pack_ms
                ph["compile_ms"] = ph.get("compile_ms", 0.0) + compile_ms
                ph["solve_ms"] = ph.get("solve_ms", 0.0) + solve_ms
                ph["readback_ms"] = ph.get("readback_ms", 0.0) + readback_ms
                r.ticket.t_mark = t_read
            q_ms = [
                (fl.t0 - r.ticket.t_submit) * 1e3 for r in reqs
            ]
            cache_d = plan_cache.delta(fl.snap)
            telemetry.record(
                "batch.dispatch", solver=solver, batch=nb,
                bucket=bkt, pad_waste=bkt - nb,
                queue_ms_max=round(max(q_ms), 3),
                queue_ms_mean=round(sum(q_ms) / len(q_ms), 3),
                dispatch_ms=round((time.monotonic() - fl.t0) * 1e3, 3),
                solve_ms=round(solve_ms, 3),
                compile_ms=round(compile_ms, 3),
                program=key,
                iters_max=int(iters[:nb].max(initial=0)),
                iters_mean=float(iters[:nb].mean()) if nb else 0.0,
                plan_cache=cache_d,
                n=reqs[0].pattern.shape[0], nnz=reqs[0].pattern.nnz,
                strategy=plan.strategy, S=plan.S,
                inflight=len(self._inflight),
                # measured host/device split, sampled dispatches only
                # (the axon_report programs table's device_ms column)
                **({"host_ms": round(profile_ms[0], 3),
                    "device_ms": round(profile_ms[1], 3)}
                   if profile_ms is not None else {}),
                # reduced-precision dispatches only (ISSUE 15): the
                # default 'exact' path keeps the event byte-identical
                **({"dtype_policy": fl.policy,
                    "ir_outer": ir_outer}
                   if fl.policy != mixed_mod.EXACT else {}),
            )
        if promote_lanes:
            self._promote_requeue(
                promote_lanes, fl,
                reason="nonfinite" if promote_nonfinite else "unconverged",
            )
        if requeue_lanes:
            self._requeue(requeue_lanes, dt)
        for r in reqs:
            if (r in requeue_lanes or r in promote_lanes) and (
                self._find_inflight(r.ticket) is not None
            ):
                continue  # finalizes when the fallback bucket retires
            self._finalize_ticket(r.ticket)

    # -- resilience paths --------------------------------------------------
    def _promote_requeue(self, reqs, fl, reason: str) -> None:
        """The promote_dtype rung (ISSUE 15, docs/resilience.md): an
        anomalous reduced-precision bucket re-solves its failed lanes
        at ``'exact'`` — same solver, same preconditioner — and the
        whole (pattern, solver, bucket, dtype) group is pinned to
        'exact' for the rest of the session (the health-monitor
        escalation riding the existing requeue machinery). The classic
        solver-escalation rung stays available BEHIND it: an exact
        re-solve that still fails takes the gmres-at-promoted-dtype
        fallback like any other lane."""
        pattern = reqs[0].pattern
        self.dtype_policy.promote(
            pattern, fl.solver, fl.bkt, fl.dt, reason=reason
        )
        _REQUEUES.inc(len(reqs))
        if telemetry.enabled():
            telemetry.record(
                "mixed.promote", reason=reason, lanes=len(reqs),
                solver=fl.solver, bucket=fl.bkt, from_policy=fl.policy,
                program=fl.key, tickets=[r.ticket.id for r in reqs],
            )
            telemetry.record(
                "batch.requeue", solver=fl.solver, lanes=len(reqs),
                from_solver=fl.solver, action="promote_dtype",
                dtype=np.dtype(fl.dt).str,
                tickets=[r.ticket.id for r in reqs],
            )
        fb = [
            _Request(r.pattern, r.values, r.b, r.tol, None, None,
                     r.ticket, precond=r.precond,
                     dtype_policy=mixed_mod.EXACT)
            for r in reqs
        ]
        try:
            self._dispatch(fb, fl.dt, solver=fl.solver,
                           allow_requeue=fl.allow_requeue,
                           precond=reqs[0].precond,
                           dtype_policy=mixed_mod.EXACT)
        except Exception:  # noqa: BLE001 - first results already stand
            # best-effort like the classic rung: every lane already
            # holds its first (unconverged) result
            pass

    def _requeue(self, reqs, dt) -> None:
        """Failed-lane requeue: one fallback bucket under the safer
        solver/dtype; the fallback result only replaces a lane's first
        result when it is better (``SolveTicket._offer``)."""
        fb_dt = _promote(dt)
        _REQUEUES.inc(len(reqs))
        if telemetry.enabled():
            # explicit tickets: the enclosing dispatch scope covers the
            # WHOLE original bucket, this event is about the requeued
            # lanes only
            telemetry.record(
                "batch.requeue", solver=self.fallback_solver,
                lanes=len(reqs), from_solver=self.solver,
                dtype=np.dtype(fb_dt).str,
                tickets=[r.ticket.id for r in reqs],
            )
        # fresh maxiter budget: the lane may have failed BECAUSE the
        # caller's budget was too small for the requested solver.
        # The fallback bucket also DROPS the preconditioner (ISSUE 14,
        # the session-level drop rung of docs/resilience.md): a
        # nonfinite lane may owe its corruption to M's factorization —
        # the safer re-solve must not reuse it.
        fb = [
            _Request(r.pattern, r.values, r.b, r.tol, None, None, r.ticket,
                     precond="off")
            for r in reqs
        ]
        try:
            self._dispatch(fb, fb_dt, solver=self.fallback_solver,
                           allow_requeue=False, precond="off")
        except Exception:  # noqa: BLE001 - first results already stand
            # the requeue is best-effort: every lane already holds its
            # first (unconverged) result, which result() returns
            pass

    # -- elastic mesh (ISSUE 20, docs/resilience.md "Elastic topology") ----
    def remesh(self, mesh=None) -> dict:
        """Re-plan the session onto a new topology, migrating every
        queued and in-flight ticket (the explicit production verb; the
        forged-fault trigger rides ``_launch``). ``mesh=None`` asks the
        monitor what the world currently offers (under an active mesh
        fault that is the forged topology; otherwise — and after
        ``faults.clear()`` — the construction-time mesh, which makes
        ``remesh()`` the recovery verb of the shrink drill). Returns a
        JSON-friendly outcome dict; ``outcome='ok'`` carries the old/new
        fingerprints, lanes requeued and programs warm-replayed."""
        if not self.fleet.mode:
            return {"outcome": "disabled"}
        if mesh is None:
            mesh = (
                self._elastic.resolve() if self._elastic is not None
                else fleet_mod.fleet_mesh()
            )
        return self._do_remesh(mesh, reason="manual")

    def _remesh_migrate(self, reqs, target, reason: str) -> None:
        """Zero-loss lane migration: requeue this launch's lanes into
        the pending queue — each carrying its ticket's best iterate as
        ``x0``, so work done on the old topology is kept, not redone —
        then run the full transition. The lanes re-dispatch on the new
        topology at the next pipeline drive (flush/drain/result())."""
        _REQUEUES.inc(len(reqs))
        if telemetry.enabled():
            telemetry.record(
                "batch.requeue", solver=self.solver, lanes=len(reqs),
                from_solver=self.solver, action="remesh",
                tickets=[r.ticket.id for r in reqs],
            )
        for r in reqs:
            x0 = r.ticket._out[0] if r.ticket._out is not None else r.x0
            self._pending.setdefault(id(r.pattern), []).append(
                _Request(r.pattern, r.values, r.b, r.tol, x0, r.maxiter,
                         r.ticket, precond=r.precond,
                         dtype_policy=r.dtype_policy,
                         precond_dtype=r.precond_dtype)
            )
        self._do_remesh(target, reason=reason, requeued=len(reqs))

    def _reset_occupancy(self) -> None:
        """Drop the per-device occupancy gauges wholesale: after a
        shrink the old mesh's higher-numbered device series would
        linger as ghosts — and a zeroed ghost still trips occupancy
        alerting, so the family is REMOVED, not reset. The next
        dispatch repopulates it from the live plan."""
        self._device_occ = []
        _metrics.remove("fleet.device_occupancy")

    def _do_remesh(self, target, reason: str, requeued: int = 0) -> dict:
        """One topology transition, in the only legal order: quiesce
        (admission hold + retire every in-flight bucket, so no program
        compiled against the old topology is still running), charge the
        flap guard, re-target the :class:`FleetPolicy`, reset the
        device-keyed gauges, and warm-replay the manifest against the
        new fingerprint (mesh-keyed entries make the re-plan warm
        whenever this topology was ever seen before — shrink then
        recover is two warm replays, zero serving builds)."""
        if self._remeshing:
            return {"outcome": "reentrant"}
        if self._elastic is not None and self._elastic.latched:
            return {"outcome": "latched"}
        old_fp = self.fleet.fingerprint
        if self.fleet.mesh is not None and (
            elastic_mod.mesh_identity(target)
            == elastic_mod.mesh_identity(self.fleet.mesh)
        ):
            return {"outcome": "noop"}
        self._remeshing = True
        t0 = time.monotonic()
        try:
            # quiesce: the admission hold — everything in flight retires
            # before the policy re-points, and the hold is visible as an
            # ordinary admission event with reason='remesh'
            depth = self._unfinalized
            while self._inflight:
                self._retire(self._inflight.popleft())
            if telemetry.enabled():
                telemetry.record(
                    "batch.admission", mode="block", reason="remesh",
                    depth=depth,
                    waited_ms=round((time.monotonic() - t0) * 1e3, 3),
                )
            if self._elastic is not None and self._elastic.guard():
                # flap budget exhausted: stop chasing the topology —
                # pin the single-device strategy and serve degraded
                self.fleet.pin_single("remesh flap guard")
                _metrics.counter(
                    "fleet.remeshes", outcome="latched",
                    help=_REMESHES_HELP,
                ).inc()
                if telemetry.enabled():
                    telemetry.record(
                        "fleet.remesh_failed", reason="flap_guard",
                        old=old_fp,
                        remeshes=self._elastic.remeshes,
                        retries=self._elastic.retries,
                    )
                self._reset_occupancy()
                return {"outcome": "latched", "old": old_fp}
            from ..parallel.mesh import mesh_fingerprint

            new_fp = mesh_fingerprint(target)
            if new_fp == old_fp:
                # a swap: same fingerprint, different devices — cached
                # program keys would collide with executables compiled
                # against the dead mesh, so their entries must go
                for p in self._patterns.values():
                    plan_cache.invalidate(p)
            self.fleet.retarget(target)
            self._reset_occupancy()
            from .. import vault

            replayed = (
                self._replay_manifest() if vault.enabled() else 0
            )
            _metrics.counter(
                "fleet.remeshes", outcome="ok", help=_REMESHES_HELP,
            ).inc()
            devices = len(list(target.devices.flat))
            wall = round((time.monotonic() - t0) * 1e3, 3)
            if telemetry.enabled():
                telemetry.record(
                    "fleet.remesh", old=old_fp, new=new_fp,
                    reason=reason, requeued=requeued,
                    replayed=replayed, devices=devices, wall_ms=wall,
                )
            return {
                "outcome": "ok", "old": old_fp, "new": new_fp,
                "reason": reason, "requeued": requeued,
                "replayed": replayed, "devices": devices,
                "wall_ms": wall,
            }
        finally:
            self._remeshing = False

    def _solve_degraded(self, reqs, dt, solver: str) -> None:
        """Per-lane eager fallback when the compiled bucket program is
        unavailable: each lane solves through the plain linalg drivers
        over a csr view of the pattern; per-lane failures fail only that
        lane's ticket."""
        from ..utils import asjnp

        pattern = reqs[0].pattern
        indices = asjnp(pattern.indices)
        indptr = asjnp(pattern.indptr)
        for r in reqs:
            # narrow the trace context to the one lane being solved so
            # the eager solvers' events attribute per request
            with telemetry.ticket_scope(r.ticket.id):
                self._solve_degraded_lane(
                    r, dt, solver, indices, indptr, pattern
                )

    def _solve_degraded_lane(self, r, dt, solver, indices, indptr,
                             pattern) -> None:
        from .. import linalg
        from ..csr import csr_array
        from ..utils import asjnp

        try:
            A = csr_array.from_parts(
                asjnp(r.values.astype(dt)), indices, indptr,
                pattern.shape,
            )
            b = asjnp(r.b.astype(dt))
            maxiter = (
                r.maxiter if r.maxiter is not None
                else pattern.shape[0] * 10
            )
            if solver == "gmres":
                x, iters = linalg.gmres(
                    A, b, tol=0.0, atol=r.tol, restart=self.restart
                )
            elif solver == "bicgstab":
                x, iters = linalg.bicgstab(
                    A, b, tol=r.tol, maxiter=maxiter
                )
            else:
                x, iters = linalg.cg(A, b, tol=r.tol, maxiter=maxiter)
            resid2 = float(
                np.linalg.norm(r.b - np.asarray(A @ asjnp(np.asarray(x))))
                ** 2
            )
            r.ticket._offer(
                np.asarray(x), iters, resid2,
                np.isfinite(resid2) and resid2 <= r.tol ** 2,
                solver=solver,
            )
        except Exception as e:  # noqa: BLE001 - lane isolation
            r.ticket._fail(e)

    def _build_program(self, pattern: SparsityPattern, bkt: int, dt,
                       solver: str | None = None, plan=None,
                       precond: str = precond_mod.NONE,
                       dtype_policy: str = mixed_mod.EXACT,
                       precond_dtype: str = "compute"):
        """The per-bucket compiled program: pattern pack + masked solver
        loop under ONE ``jax.jit`` whose arguments are the value stack,
        rhs, x0 and tolerances — so same-bucket dispatches with fresh
        coefficients reuse the executable (no constants captured from
        any particular batch).

        ``plan`` routes the fleet strategies (ISSUE 10): 'batch' wraps
        the SAME loop cores in a ``shard_map`` over the mesh batch axis
        with the psum all-converged exit (gmres shards its inputs and
        lets GSPMD partition the host-driven cycle), 'row' wraps
        ``DistCSR``/``dist_cg`` in a B=1 bucket signature. 'single' (or
        ``None``) is byte-identical to the classic path.

        ``precond`` is the resolved preconditioner kind (ISSUE 14):
        pattern-level maps build HERE on the host (plan-cached,
        vault-persisted), the numeric factorization compiles INTO the
        program from its ``values`` argument, so every dispatch
        factorizes fresh coefficients on device. 'none' leaves the
        program byte-identical to the historic unpreconditioned one.

        ``dtype_policy`` is the resolved precision policy (ISSUE 15):
        a reduced policy ('f32ir' | 'bf16ir') swaps the solver loop for
        the fused iterative-refinement program — values downcast to the
        storage dtype INSIDE the program (one elementwise op; the inner
        sweep's packed planes and vectors then carry the narrow dtype
        with wide accumulation), the f64 outer loop verifies and
        corrects, and the program returns a 5th output (the refinement
        sweep count). 'exact' leaves every program byte-identical to
        the historic one.

        ``precond_dtype`` (ISSUE 16): 'storage' on a reduced-precision
        program builds the preconditioner factory with the policy's
        ``storage_dtype``/``acc_dtype`` — factors factorized wide,
        STORED at the reduced width, applied with wide accumulation —
        so M's memory traffic compounds with the value planes' ('.W'
        key suffix). 'compute' (the default, and the forced value
        everywhere the combination can't apply) changes nothing."""
        solver = solver or self.solver
        if plan is not None and plan.strategy == "row":
            return fleet_mod.build_row_program(
                pattern, dt, plan.mesh,
                conv_test_iters=self.conv_test_iters,
                make_M=self.row_precond,
            )
        if precond == precond_mod.NONE:
            mfac = None
        elif (precond_dtype == "storage"
              and dtype_policy != mixed_mod.EXACT):
            m_sdt, m_adt = mixed_mod.inner_dtypes(dtype_policy)
            mfac = self.precond.factory(
                pattern, precond, storage_dtype=m_sdt, acc_dtype=m_adt
            )
        else:
            mfac = self.precond.factory(pattern, precond)
        mixed = None
        if dtype_policy != mixed_mod.EXACT:
            mixed = dict(
                policy=dtype_policy,
                **self.dtype_policy.ir_knobs(
                    dtype_policy, pattern.shape[0], self.conv_test_iters
                ),
            )
        if plan is not None and plan.strategy == "batch":
            return fleet_mod.build_batch_program(
                pattern, bkt, dt, solver, plan.mesh,
                self.conv_test_iters,
                gmres_inner=(
                    self._build_gmres_program(pattern, bkt, dt,
                                              precond=precond)
                    if solver == "gmres" else None
                ),
                m_factory=mfac,
                mixed=mixed,
            )
        if solver == "gmres":
            return self._build_gmres_program(pattern, bkt, dt,
                                             precond=precond)
        pack = pattern.sell_pack()
        idx_slabs, pos, zero_rows = (
            pack.idx_slabs, pack.pos, pack.plan.zero_rows
        )
        if mixed is not None:
            return _build_ir_program(
                pack, mixed, solver, self.conv_test_iters, mfac,
                precond_dtype=precond_dtype,
            )
        loop = (
            krylov._cg_loop if solver == "cg"
            else krylov._bicgstab_loop
        )
        cti = self.conv_test_iters

        # donated value-stack/rhs/x0 (TPU/GPU only — see donate_argnums):
        # the staged uploads are consumed exactly once per dispatch, so
        # XLA recycles their HBM for outputs/temps instead of holding
        # input + output footprints for every in-flight bucket
        @partial(jax.jit, donate_argnums=donate_argnums())
        def run(values, rhs, x0, tols, maxiter):
            vals = pack.pack_values(values)

            def mv(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals, pos, X, zero_rows
                )

            fmv = krylov._maybe_faulty_mv(mv)
            # batched numeric factorization from THIS dispatch's value
            # stack (ISSUE 14) — pattern maps are closure constants
            Mvec = None if mfac is None else mfac(values, fmv)
            return loop(fmv, rhs, x0, tols, maxiter, cti, Mvec=Mvec)

        return run

    def _build_gmres_program(self, pattern, bkt, dt,
                             precond: str = precond_mod.NONE):
        """GMRES keeps its host-driven outer restart loop, so the bucket
        'program' is a closure dispatching :func:`krylov.batched_gmres`
        over a pattern-packed operator — restart cycles still compile
        once per bucket (the jitted cycle is rebuilt per dispatch; the
        XLA executable comes from jax's compile cache). ``precond``
        resolves to a left preconditioner of the batched cycle."""
        restart = self.restart

        restart_eff = restart or min(20, pattern.shape[0])
        mfac = (
            None if precond == precond_mod.NONE
            else self.precond.factory(pattern, precond)
        )

        def run(values, rhs, x0, tols, maxiter):
            op = BatchedCSR(pattern, values)
            M = (
                None if mfac is None
                else mfac(jnp.asarray(values), op.matvec)
            )
            # batched_gmres takes a scalar-or-(B,) relative tol; the
            # session's per-lane ABSOLUTE targets ride the atol floor.
            # Its maxiter counts OUTER restarts; bound inner work by the
            # session's maxiter contract.
            outer = max(-(-int(maxiter) // restart_eff), 1)
            X, info = krylov.batched_gmres(
                op, rhs, x0=x0, tol=0.0, atol=tols, restart=restart_eff,
                maxiter=outer, M=M,
            )
            return X, info.iters, info.resid2, info.converged

        return run
