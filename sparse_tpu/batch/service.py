"""SolveSession: a resilient microbatching front door for same-pattern solves.

The serving loop this subsystem exists for: requests ``(A-values, b,
tol)`` trickle in from many callers, almost all of them over a handful
of sparsity patterns (the deployed meshes/graphs). The session queues
them, coalesces same-pattern requests into bucketed batches
(:mod:`sparse_tpu.batch.bucket`), dispatches each bucket through ONE
compiled masked-Krylov program (:mod:`sparse_tpu.batch.krylov`), and
scatters per-lane results back to their tickets.

Compile-count control is the whole game: the per-bucket program — the
pattern's packed SELL matvec closed inside a jitted solver loop — lives
in :mod:`sparse_tpu.plan_cache` keyed ``(pattern, "batch.<solver>.B<bucket>...")``,
so a bucket costs exactly ONE cache miss (pack + trace + compile) ever,
and every later dispatch of that bucket is a cache hit straight into a
warm executable. ``plan_cache.stats()`` is the always-on instrument;
with telemetry enabled each dispatch additionally emits a
``batch.dispatch`` event (batch size, bucket, padding waste, queue
latency, per-lane iteration stats — docs/batching.md).

Resilience (ISSUE 5, docs/resilience.md): tickets carry an explicit
:class:`TicketState` and per-ticket deadlines; ``flush()`` is
exception-safe (one failed bucket program marks ITS tickets failed and
every other bucket still dispatches); lanes that come back unconverged
or nonfinite requeue ONCE into a fallback bucket (safer solver —
default GMRES — at a promoted dtype), emitting ``batch.requeue``; and
when the compiled-program path itself is unavailable (Pallas lowering
gone, plan-cache failure, injected dispatch faults) the bucket degrades
to per-lane eager solves rather than stranding its tickets
(``batch.degraded``).

Fleet serving tier (ISSUE 10, docs/batching.md "Serving across a
mesh"): with ``SPARSE_TPU_FLEET=auto`` (or ``fleet=`` at construction)
a per-(pattern, bucket) policy (:mod:`sparse_tpu.fleet`) shards
dispatches over the device mesh — same-pattern buckets batch-shard
their lane stacks across the mesh batch axis (per-lane results
bit-identical, the all-converged exit a measured lane-count psum),
single oversized systems row-shard through ``DistCSR``/``dist_cg`` as
B=1 bucket programs. Program keys gain the mesh fingerprint, vault
manifest entries record it (a different-topology restart cold-starts
cleanly), and ``session_stats()`` reports the mesh shape plus
per-device lane occupancy.

Request-scoped observability (ISSUE 6, Axon v3): every ticket carries a
process-unique id (``telemetry.new_ticket_id``); each dispatch runs
inside a :func:`telemetry.ticket_scope` so EVERY event it causes —
``batch.dispatch``, a ``kernel.failover`` five layers down,
``fault.injected``, ``batch.requeue`` — carries the originating ids;
and flush resolution emits one ``batch.ticket`` terminal event per
request with the end-to-end latency and its phase breakdown (queue wait
→ pack → compile → solve → readback). Latencies feed the always-on
``batch.ticket_latency`` histogram (per solver) and, when the session
has an ``slo_ms`` target, the ``batch.slo_misses`` counter — the
percentiles/SLO surface ``scripts/axon_report.py`` rolls up and the
live exporter (``telemetry.serve()``) scrapes. Bucket-program builds
route through :mod:`telemetry._cost <sparse_tpu.telemetry._cost>` so
each (pattern, solver, bucket, dtype) program's compile wall-clock and
XLA cost/memory analysis land in ``plan_cache.compile`` events.
"""

from __future__ import annotations

import enum
import time
import weakref

import jax
import jax.numpy as jnp
import numpy as np

from .. import fleet as fleet_mod
from .. import plan_cache, telemetry
from ..config import settings
from ..ops import spmv as spmv_ops
from ..parallel import comm as _comm
from ..resilience import faults as _faults
from ..telemetry import _cost, _metrics, _profiler
from . import bucket as bucketing
from . import krylov
from .operator import BatchedCSR, SparsityPattern

_SOLVERS = ("cg", "bicgstab", "gmres")

# Always-on session levels (telemetry/_metrics.py — scrapeable via
# telemetry.metrics_text()): queued-request depth across all live
# sessions, real-lanes-per-bucket occupancy ratio, and dispatch count.
_QUEUE_DEPTH = _metrics.gauge("batch.queue_depth")
_BUCKET_OCCUPANCY = _metrics.histogram("batch.bucket_occupancy")
_DISPATCHES = _metrics.counter("batch.dispatches")
_PAD_WASTE = _metrics.counter("batch.pad_lanes")
# resilience levels
_REQUEUES = _metrics.counter("batch.requeues")
_DEGRADED = _metrics.counter("batch.degraded")
_BUCKET_FAILURES = _metrics.counter("batch.bucket_failures")
_DEADLINE_FAILED = _metrics.counter("batch.deadline_failed")
# serving levels (ISSUE 6): end-to-end ticket latency (seconds, per
# final solver) and SLO misses across all sessions with an slo_ms target
_SLO_MISSES = _metrics.counter(
    "batch.slo_misses",
    help="tickets whose end-to-end latency exceeded the session slo_ms",
)
_TICKET_LATENCY_HELP = (
    "end-to-end ticket latency in seconds (submit -> resolved)"
)

# live sessions, weakly held: the /session serving endpoint
# (telemetry/_serve.py) reads their stats without keeping them alive
_SESSIONS: "weakref.WeakSet" = weakref.WeakSet()


def sessions_stats() -> list:
    """``session_stats()`` of every live session (the ``/session``
    exporter endpoint's payload; order is not meaningful)."""
    return [s.session_stats() for s in list(_SESSIONS)]


class TicketState(enum.Enum):
    """Lifecycle of a submitted system (ISSUE 5 satellite: unresolved
    and failed tickets used to be indistinguishable bare RuntimeErrors)."""

    PENDING = "pending"
    DONE = "done"
    FAILED = "failed"


class TicketError(RuntimeError):
    """Base of the ticket error family."""


class TicketUnresolvedError(TicketError):
    """``result()`` on a ticket no flush has resolved (should not happen
    through the public API — flush resolves or fails every ticket)."""


class TicketFailedError(TicketError):
    """The ticket's bucket failed (program error, exhausted dispatch
    retries); ``__cause__`` carries the underlying exception."""


class TicketDeadlineError(TicketFailedError):
    """The ticket's deadline passed before its bucket dispatched."""


class InjectedDispatchFailure(RuntimeError):
    """A ``drop:dispatch`` fault clause fired (resilience.faults) — the
    injected stand-in for a dispatch lost to a worker/backend failure."""


class SolveTicket:
    """Handle for one submitted system. ``result()`` flushes the session
    if the request is still queued, then returns ``(x, iters, resid2)``
    (host numpy scalars/arrays for the lane). Failed tickets raise
    :class:`TicketFailedError` (:class:`TicketDeadlineError` for
    deadline misses) instead of returning garbage.

    ``id`` is the process-unique trace id every event the ticket causes
    carries (``telemetry.ticket_scope``); ``phase_ms`` accumulates the
    per-phase latency breakdown (queue/pack/compile/solve/readback)
    across the first dispatch and any requeue, and is what the
    ``batch.ticket`` terminal event and the Perfetto ticket lane render.

    ``tenant`` is the optional caller label fairness rollups group by
    (ISSUE 11 satellite): it rides the ``batch.ticket`` terminal event
    and labels the ``batch.ticket_latency`` histogram; ``None`` (the
    default) keeps the existing metric series names unchanged."""

    __slots__ = ("_session", "_out", "t_submit", "state", "error",
                 "deadline_s", "requeued", "solver", "id", "phase_ms",
                 "t_done", "t_mark", "tenant")

    def __init__(self, session, deadline_s=None, tenant=None):
        self._session = session
        self._out = None
        self.t_submit = time.monotonic()
        self.state = TicketState.PENDING
        self.error = None
        self.deadline_s = None if deadline_s is None else float(deadline_s)
        self.requeued = False
        self.solver = None  # the solver that produced the final result
        self.id = telemetry.new_ticket_id()
        self.phase_ms: dict = {}
        self.t_done = None  # set once, at first terminal resolution
        self.t_mark = None  # end of the last phase-accounted dispatch
        self.tenant = None if tenant is None else str(tenant)

    @property
    def done(self) -> bool:
        return self.state is TicketState.DONE

    @property
    def failed(self) -> bool:
        return self.state is TicketState.FAILED

    @property
    def expired(self) -> bool:
        return (
            self.deadline_s is not None
            and time.monotonic() - self.t_submit >= self.deadline_s
        )

    def _offer(self, x, iters, resid2, converged, solver=None):
        """Install a result, keeping the better one when a fallback
        dispatch re-solves the lane (converged beats unconverged, then
        smaller residual; a FAILED ticket is revived by any result)."""
        new = (x, int(iters), float(resid2), bool(converged))
        if self._out is not None:
            old = self._out
            better = (new[3] and not old[3]) or (
                new[3] == old[3]
                and (np.isfinite(new[2]) and not np.isfinite(old[2])
                     or (np.isfinite(new[2]) and np.isfinite(old[2])
                         and new[2] < old[2]))
            )
            if not better:
                return
        self._out = new
        self.state = TicketState.DONE
        self.error = None
        if solver is not None:
            self.solver = solver

    def _fail(self, exc) -> None:
        if self.state is TicketState.DONE:
            return  # a resolved ticket never regresses to failed
        self.state = TicketState.FAILED
        self.error = exc

    def result(self):
        if self.state is TicketState.PENDING:
            self._session.flush()
        if self.state is TicketState.FAILED:
            raise (
                self.error
                if isinstance(self.error, TicketError)
                else TicketFailedError(
                    f"bucket dispatch failed: {self.error!r}"
                )
            ) from (self.error if isinstance(self.error, Exception) else None)
        if self._out is None:
            raise TicketUnresolvedError(
                "flush did not resolve this ticket"
            )
        return self._out[:3]

    @property
    def converged(self) -> bool:
        if self.state is TicketState.PENDING:
            self._session.flush()
        if self._out is None:
            return False
        return self._out[3]


class _Request:
    __slots__ = ("pattern", "values", "b", "tol", "x0", "maxiter", "ticket")

    def __init__(self, pattern, values, b, tol, x0, maxiter, ticket):
        self.pattern, self.values, self.b = pattern, values, b
        self.tol, self.x0, self.maxiter = tol, x0, maxiter
        self.ticket = ticket


def _promote(dt: np.dtype) -> np.dtype:
    """The requeue bucket's 'safer dtype': one precision step up."""
    dt = np.dtype(dt)
    if dt == np.float32:
        return np.dtype(np.float64)
    if dt == np.complex64:
        return np.dtype(np.complex128)
    return dt


class SolveSession:
    """Queue -> coalesce -> bucket -> dispatch -> scatter.

    Parameters
    ----------
    solver : 'cg' | 'bicgstab' | 'gmres'
    batch_max : max lanes per dispatched batch (default
        ``settings.batch_max``)
    bucket_policy : 'pow2' | 'exact' (default ``settings.batch_bucket``)
    conv_test_iters : convergence-test cadence of the masked loops
    restart : GMRES restart length (gmres only)
    auto_flush : when set, ``submit`` flushes as soon as a pattern has
        this many queued requests (a latency/throughput knob; None =
        explicit ``flush()`` only)
    requeue : requeue unconverged/nonfinite lanes once into a fallback
        bucket (``fallback_solver`` at a promoted dtype); on by default
    fallback_solver : solver of the requeue bucket (default 'gmres' —
        the most breakdown-tolerant of the three)
    dispatch_attempts : tries per bucket before its tickets fail (>= 1;
        retries cover transient dispatch faults, e.g. injected drops)
    slo_ms : the session's end-to-end latency objective per ticket
        (submit -> resolved, milliseconds). Purely observational: a
        ticket over the target still returns normally, but counts into
        ``batch.slo_misses`` and its ``batch.ticket`` terminal event is
        flagged ``slo_miss`` (None = no objective, nothing counted)
    warm_start : replay the vault's warm-start manifest on construction
        (ISSUE 9, docs/performance.md): hot (pattern, solver, bucket,
        dtype) programs from previous processes re-load their pattern
        packs from the disk tier and re-build/compile ahead of traffic,
        so serving-path dispatches start at zero plan-cache misses.
        Default ``None`` = replay iff the vault is enabled
        (``SPARSE_TPU_VAULT``); ``False`` always skips. Replay is
        best-effort — a corrupt manifest or artifact degrades to an
        ordinary cold start, never a construction failure.
    profile_every : sampled timed-dispatch device profiling (ISSUE 12):
        every Nth dispatched bucket splits its solve wall clock into
        host (async dispatch) vs device (``block_until_ready``) time,
        feeding the always-on ``batch.program_device_ms{program}``
        histogram, the cost table's measured columns and the
        ``batch.dispatch`` event's ``device_ms``/``host_ms`` fields.
        Default ``None`` = ``settings.profile_every``
        (``SPARSE_TPU_PROFILE_EVERY``); 0 = off — no extra timestamps,
        identical compiled programs either way.
    """

    def __init__(self, solver: str = "cg", batch_max: int | None = None,
                 bucket_policy: str | None = None, conv_test_iters: int = 25,
                 restart: int | None = None, auto_flush: int | None = None,
                 requeue: bool = True, fallback_solver: str = "gmres",
                 dispatch_attempts: int = 2, slo_ms: float | None = None,
                 warm_start: bool | None = None, fleet=None,
                 fleet_mesh=None, fleet_min_b: int | None = None,
                 row_shard_min_n: int | None = None,
                 profile_every: int | None = None):
        if solver not in _SOLVERS:
            raise ValueError(f"solver must be one of {_SOLVERS}")
        if fallback_solver not in _SOLVERS:
            raise ValueError(f"fallback_solver must be one of {_SOLVERS}")
        self.solver = solver
        self.batch_max = int(batch_max or settings.batch_max)
        self.bucket_policy = bucket_policy or settings.batch_bucket
        self.conv_test_iters = int(conv_test_iters)
        self.restart = restart
        self.auto_flush = auto_flush
        self.requeue = bool(requeue)
        self.fallback_solver = fallback_solver
        self.dispatch_attempts = max(int(dispatch_attempts), 1)
        self.slo_ms = None if slo_ms is None else float(slo_ms)
        # sampled timed-dispatch device profiling (ISSUE 12): every Nth
        # dispatch splits solve wall clock at the dispatch-return
        # boundary into host vs device time (telemetry/_profiler.py).
        # 0 (the default env) = off: no extra timestamps, no extra
        # event fields, and the compiled programs are identical either
        # way — sampling never enters a trace.
        self.profile_every = (
            settings.profile_every if profile_every is None
            else max(int(profile_every), 0)
        )
        self._dispatch_seq = 0
        # mesh-sharded serving tier (ISSUE 10, docs/batching.md): the
        # per-(pattern, bucket) strategy policy. `fleet` may be a mode
        # string ('auto'/'batch'/'row'), True/False, a ready FleetPolicy,
        # or None = settings.fleet (SPARSE_TPU_FLEET). Off (the default
        # env) leaves every code path byte-identical to the classic
        # single-device session.
        self.fleet = fleet_mod.FleetPolicy.resolve(
            fleet, mesh=fleet_mesh, min_b=fleet_min_b,
            row_min_n=row_shard_min_n,
        )
        # per-device real-lane occupancy of the most recent dispatch
        # (the /session device dimension; also on the always-on
        # fleet.device_occupancy gauge family)
        self._device_occ: list = []
        self._patterns: dict = {}  # fingerprint -> SparsityPattern (dedupe)
        self._pending: dict = {}  # id(pattern) -> [Request]
        self.dispatches = 0
        # terminal-state tallies for the /session serving endpoint
        self._ticket_counts = {"done": 0, "failed": 0, "slo_miss": 0}
        _SESSIONS.add(self)
        # serving-path persistent XLA compile cache (ISSUE 9 satellite):
        # env-gated so bucket-program executables survive restarts
        # alongside the vault's packed artifacts
        if settings.compile_cache:
            from ..utils import enable_compilation_cache

            enable_compilation_cache(settings.compile_cache)
        self.warm_replayed = 0
        from .. import vault

        if (vault.enabled() if warm_start is None else warm_start):
            if vault.enabled():
                self.warm_replayed = self._replay_manifest()

    # -- intake ------------------------------------------------------------
    def pattern_of(self, A) -> SparsityPattern:
        """Session-deduped pattern for ``A``: same structure => same
        object => same plan-cache entries across callers."""
        p = SparsityPattern.from_csr(A)
        return self._patterns.setdefault(p.fingerprint, p)

    def submit(self, A, b, tol: float = 1e-8, x0=None, maxiter=None,
               pattern: SparsityPattern | None = None,
               deadline_s: float | None = None,
               tenant: str | None = None) -> SolveTicket:
        """Queue one system. ``A`` is a CSR-shaped matrix (csr_array /
        scipy) or, with ``pattern=`` given, a bare ``(nnz,)`` value
        vector over that pattern. ``deadline_s`` is a per-ticket wall
        budget measured from submission: a ticket still queued when its
        deadline passes fails with :class:`TicketDeadlineError` instead
        of dispatching stale work. ``tenant`` stamps an optional caller
        label onto the ticket, its ``batch.ticket`` terminal event and
        the ``batch.ticket_latency`` histogram labels (ISSUE 11: the
        fairness dimension; ``None`` keeps every existing metric series
        name unchanged) — it never enters the compiled program or its
        plan-cache key."""
        if pattern is None:
            pattern = self.pattern_of(A)
            values = np.asarray(A.data if hasattr(A, "data") else A)
        else:
            pattern = self._patterns.setdefault(
                pattern.fingerprint, pattern
            )
            values = np.asarray(A)
        if values.shape != (pattern.nnz,):
            raise ValueError(
                f"values shape {values.shape} != (nnz={pattern.nnz},)"
            )
        b = np.asarray(b)
        if b.shape != (pattern.shape[0],):
            raise ValueError(
                f"rhs shape {b.shape} != ({pattern.shape[0]},)"
            )
        t = SolveTicket(self, deadline_s=deadline_s, tenant=tenant)
        q = self._pending.setdefault(id(pattern), [])
        q.append(_Request(pattern, values, b, float(tol), x0, maxiter, t))
        _QUEUE_DEPTH.inc()
        if self.auto_flush is not None and len(q) >= self.auto_flush:
            self.flush()
        return t

    @property
    def pending(self) -> int:
        return sum(len(q) for q in self._pending.values())

    def session_stats(self) -> dict:
        """JSON-friendly live view of this session (the ``/session``
        exporter endpoint aggregates these across live sessions).

        ``mesh`` is the active serving-mesh shape (ISSUE 10 satellite:
        the stats used to have no device dimension at all) and
        ``device_occupancy`` the per-device real-lane occupancy of the
        most recent dispatch — ``[real/slot]`` per device for sharded
        buckets, a single entry for the single-device path."""
        return {
            "solver": self.solver,
            "fallback_solver": self.fallback_solver,
            "batch_max": self.batch_max,
            "bucket_policy": self.bucket_policy,
            "slo_ms": self.slo_ms,
            "patterns": len(self._patterns),
            "dispatches": self.dispatches,
            "mesh": self.fleet.describe(),
            "device_occupancy": list(self._device_occ),
            "tickets": {"pending": self.pending, **self._ticket_counts},
        }

    # -- warm restart (ISSUE 9) --------------------------------------------
    def _replay_manifest(self) -> int:
        """Replay the vault's warm-start manifest: for every recorded
        hot (pattern, solver, bucket, dtype) program, load the pattern
        structure + SELL pack from the disk tier and rebuild/compile the
        bucket program ahead of traffic. Returns the number of programs
        replayed; every failure skips its entry (a warm start is an
        optimization, never a liability)."""
        from .. import vault

        t0 = time.monotonic()
        entries = vault.manifest_entries()
        replayed = 0
        mesh_skipped = 0
        for e in entries:
            try:
                solver = e.get("solver")
                bkt = int(e.get("bucket", 0))
                dtstr = e.get("dtype", "")
                if solver not in _SOLVERS or bkt < 1 or not dtstr:
                    continue
                # mesh-keyed entries (the fleet tier) only replay on the
                # SAME topology: a fingerprint mismatch — restart on a
                # different pod shape, fleet turned off — skips the
                # entry for a clean cold start instead of compiling a
                # program this mesh cannot dispatch
                mesh_fp = e.get("mesh")
                if mesh_fp:
                    if not (
                        self.fleet.enabled
                        and mesh_fp == self.fleet.fingerprint
                    ):
                        mesh_skipped += 1
                        continue
                    plan = self.fleet.plan_for(e.get("strategy", "batch"))
                else:
                    plan = fleet_mod.FleetPlan("single")
                dt = np.dtype(dtstr)
                pat = vault.load_pattern(e.get("pattern", ""))
                if pat is None:
                    continue
                pat = self._patterns.setdefault(pat.fingerprint, pat)
                pat.sell_pack()  # disk-tier hit (or rebuild + deposit)
                self._prebuild(pat, solver, bkt, dt, plan=plan)
                replayed += 1
            except Exception:  # noqa: BLE001 - entry isolation
                continue
        if replayed:
            _metrics.counter("vault.replayed").inc(replayed)
        if telemetry.enabled():
            telemetry.record(
                "vault.replay", entries=len(entries), programs=replayed,
                mesh_skipped=mesh_skipped,
                wall_ms=round((time.monotonic() - t0) * 1e3, 3),
            )
        return replayed

    def _prebuild(self, pattern: SparsityPattern, solver: str, bkt: int,
                  dt, plan=None) -> None:
        """Build (and AOT-compile, via the usual cost attribution) one
        bucket program outside any dispatch — argument shapes/dtypes
        mirror ``_dispatch`` exactly (including the fleet strategy's
        mesh-fingerprinted key), so the first real dispatch of this
        bucket is a plan-cache hit into a warm executable."""
        dt = np.dtype(dt)
        if plan is None:
            plan = fleet_mod.FleetPlan("single")
        key = f"batch.{solver}.B{bkt}.{dt.str}{plan.key_suffix}"
        n = pattern.shape[0]
        # the same conversion pipeline as a real dispatch (np stacks ->
        # jnp.asarray), so trace signatures match under any x64 setting
        args = (
            jnp.asarray(np.zeros((bkt, pattern.nnz), dtype=dt)),
            jnp.asarray(np.zeros((bkt, n), dtype=dt)),
            jnp.asarray(np.zeros((bkt, n), dtype=dt)),
            jnp.asarray(np.zeros((bkt,), dtype=np.float64)),
            n * 10,
        )

        def build():
            tb = time.perf_counter()
            fn = self._build_program(pattern, bkt, dt, solver=solver,
                                     plan=plan)
            prog, _info = _cost.attribute(
                key, fn, args, pack_s=time.perf_counter() - tb,
                solver=solver, bucket=bkt, dtype=dt.str,
                n=n, nnz=pattern.nnz, warm_start=True,
            )
            return prog

        plan_cache.get(pattern, key, build)

    def solve_many(self, mats, rhs, tol: float = 1e-8, maxiter=None):
        """Convenience one-shot: submit a same-pattern stack, flush, and
        return ``(X (B, n), iters (B,), resid2 (B,))`` host arrays."""
        tickets = [
            self.submit(A, b, tol=tol, maxiter=maxiter)
            for A, b in zip(mats, rhs)
        ]
        self.flush()
        outs = [t.result() for t in tickets]
        return (
            np.stack([o[0] for o in outs]),
            np.asarray([o[1] for o in outs]),
            np.asarray([o[2] for o in outs]),
        )

    # -- dispatch ----------------------------------------------------------
    def flush(self) -> int:
        """Dispatch every queued request; returns the number of batches
        dispatched. Groups by (pattern, dtype), splits groups into
        ``batch_max``-sized chunks, pads each chunk to its bucket.

        Exception-safe by contract (ISSUE 5 satellite): a bucket whose
        program raises marks only ITS tickets :class:`TicketFailedError`
        (after ``dispatch_attempts`` tries) — every other pending bucket
        still dispatches, and the session stays usable."""
        dispatched = 0
        pending, self._pending = self._pending, {}
        _QUEUE_DEPTH.dec(sum(len(q) for q in pending.values()))
        for q in pending.values():
            # per-ticket deadlines: fail stale work instead of solving it
            live, expired = [], []
            for r in q:
                if r.ticket.expired:
                    r.ticket._fail(TicketDeadlineError(
                        f"deadline {r.ticket.deadline_s}s passed before "
                        "dispatch"
                    ))
                    _DEADLINE_FAILED.inc()
                    expired.append(r)
                else:
                    live.append(r)
            if expired and telemetry.enabled():
                telemetry.record(
                    "batch.deadline", solver=self.solver,
                    lanes=len(expired),
                    tickets=[r.ticket.id for r in expired],
                )
            # one group per result dtype so stacked values are homogeneous
            by_dt: dict = {}
            for r in live:
                dt = np.result_type(r.values.dtype, r.b.dtype)
                by_dt.setdefault(np.dtype(dt), []).append(r)
            for dt, reqs in sorted(by_dt.items(), key=lambda kv: kv[0].str):
                for lo in range(0, len(reqs), self.batch_max):
                    chunk = reqs[lo:lo + self.batch_max]
                    err = None
                    for _attempt in range(self.dispatch_attempts):
                        try:
                            self._dispatch(chunk, dt)
                            dispatched += 1
                            err = None
                            break
                        except Exception as e:  # noqa: BLE001 - contract
                            err = e
                            if not isinstance(e, InjectedDispatchFailure):
                                break  # real failures don't auto-retry
                    if err is not None:
                        _BUCKET_FAILURES.inc()
                        for r in chunk:
                            r.ticket._fail(err)
        # every flushed ticket is terminal now (done, failed, or
        # deadline-expired): emit its batch.ticket terminal event and
        # feed the latency/SLO surfaces exactly once per ticket
        for q in pending.values():
            for r in q:
                self._finalize_ticket(r.ticket)
        return dispatched

    def _finalize_ticket(self, t: SolveTicket) -> None:
        """Terminal accounting for one resolved ticket: end-to-end
        latency into the always-on ``batch.ticket_latency`` histogram
        (labeled by the solver that produced the result), SLO-miss
        counting against the session target, and — telemetry on — the
        ``batch.ticket`` terminal event closing the ticket's trace."""
        if t.t_done is not None:
            return  # already finalized (a requeue resolves in-flush)
        t.t_done = time.monotonic()
        latency_s = t.t_done - t.t_submit
        solver = t.solver or self.solver
        # tenant-labeled series only exist for tenant-tagged tickets:
        # the default (None) keeps the pre-existing {solver} series names
        labels = {"solver": solver}
        if t.tenant is not None:
            labels["tenant"] = t.tenant
        _metrics.histogram(
            "batch.ticket_latency", help=_TICKET_LATENCY_HELP,
            **labels,
        ).observe(latency_s)
        slo_miss = self.slo_ms is not None and latency_s * 1e3 > self.slo_ms
        if slo_miss:
            _SLO_MISSES.inc()
            self._ticket_counts["slo_miss"] += 1
        state = "done" if t.done else "failed"
        self._ticket_counts[state] += 1
        if telemetry.enabled():
            fields = {
                "ticket": t.id,
                "state": state,
                "solver": solver,
                "latency_ms": round(latency_s * 1e3, 3),
                "requeued": t.requeued,
            }
            if t.tenant is not None:
                fields["tenant"] = t.tenant
            if t.phase_ms:
                fields["phases"] = {
                    k: round(v, 3) for k, v in t.phase_ms.items()
                }
            if t.done:
                fields["converged"] = bool(t._out[3])
            if isinstance(t.error, TicketDeadlineError):
                fields["reason"] = "deadline"
            elif t.error is not None:
                fields["reason"] = repr(t.error)[:200]
            if self.slo_ms is not None:
                fields["slo_ms"] = self.slo_ms
                fields["slo_miss"] = slo_miss
            telemetry.record("batch.ticket", **fields)

    def _fleet_account(self, plan, solver, dt, nb, bkt, iters,
                       solve_s) -> None:
        """Post-dispatch fleet accounting (ISSUE 10): per-device lane
        occupancy (session stats + always-on gauges), the batch-sharded
        program's measured-collective commit (the per-iteration
        all-converged psum the shard_map trace noted), and — telemetry
        on — the ``fleet.dispatch``/``fleet.shard`` events plus the
        ``comm.measured`` reconciliation against the analytic model."""
        S = plan.S
        if plan.strategy == "row":
            # a row-sharded system spans EVERY device (row blocks), so
            # each one is fully occupied by the single lane
            occ = [1] * S
            per = 1
        else:
            occ = fleet_mod.device_lane_counts(nb, bkt, S)
            per = max(bkt // max(S, 1), 1)
        self._device_occ = [round(c / per, 4) for c in occ]
        for d, c in enumerate(occ):
            _metrics.gauge(
                "fleet.device_occupancy", device=str(d),
                help="real lanes / bucket slots on this device in the "
                "most recent dispatched bucket",
            ).set(c / per)
        if not plan.sharded:
            return
        led = None
        execs = 0
        if plan.strategy == "batch" and solver != "gmres":
            # the while-condition psum ran (global iterations + 1)
            # times; global iterations == the slowest lane's freeze
            # step (pad lanes freeze at the first test point, so the
            # max over ALL bkt lanes is exact)
            led = fleet_mod.batch_ledger(plan.fingerprint, solver, bkt, dt)
            execs = int(np.asarray(iters).max(initial=0)) + 1
            if led.entries:
                led.commit(execs, S)
        if not telemetry.enabled():
            return
        telemetry.record(
            "fleet.dispatch", strategy=plan.strategy, S=S, bucket=bkt,
            lanes=nb, solver=solver, mesh=plan.fingerprint,
            device_lanes=occ,
        )
        for d, c in enumerate(occ):
            telemetry.record(
                "fleet.shard", device=d, lanes=c, bucket_lanes=per,
                strategy=plan.strategy,
            )
        if led is not None and led.entries and execs > 1:
            _comm.record_measured(
                "fleet.batch", led, executions=execs, shards=S,
                model_bytes=fleet_mod.batch_comm_model_bytes(S, execs - 1),
                solve_s=solve_s, strategy=plan.strategy, bucket=bkt,
                solver=solver,
            )

    def _dispatch(self, reqs, dt, solver: str | None = None,
                  allow_requeue: bool = True) -> None:
        # every event this dispatch causes — batch.*, kernel.failover,
        # fault.injected, plan_cache.compile — carries the lanes' ticket
        # ids (replace semantics: a requeue re-enters with its own lanes)
        with telemetry.ticket_scope(*(r.ticket.id for r in reqs)):
            self._dispatch_scoped(reqs, dt, solver, allow_requeue)

    def _dispatch_scoped(self, reqs, dt, solver: str | None,
                         allow_requeue: bool) -> None:
        solver = solver or self.solver
        t0 = time.monotonic()
        if _faults.ACTIVE:
            for act in _faults.dispatch_actions():
                if act[0] == "drop":
                    raise InjectedDispatchFailure(
                        "injected dispatch drop (resilience.faults)"
                    )
                if act[0] == "delay":
                    time.sleep(act[1] / 1e3)
        pattern = reqs[0].pattern
        nb = len(reqs)
        # fleet strategy first, bucket second: a batch-sharded bucket
        # must round up to a mesh multiple (mesh-pad lanes carry the
        # same instant-converge contract as ordinary pad lanes and are
        # counted against the FINAL bucket below — the pad-accounting
        # bugfix), a row-sharded submission is exactly one lane
        plan = self.fleet.decide(pattern, nb, solver)
        if plan.strategy == "row":
            bkt = 1
        else:
            bkt = bucketing.bucket_batch(
                nb, policy=self.bucket_policy, batch_max=self.batch_max,
                multiple_of=(plan.S if plan.strategy == "batch" else 1),
            )
        values = np.stack([r.values.astype(dt) for r in reqs])
        rhs = np.stack([r.b.astype(dt) for r in reqs])
        tols = np.asarray([r.tol for r in reqs])
        x0 = None
        if any(r.x0 is not None for r in reqs):
            x0 = np.stack([
                np.zeros(pattern.shape[0], dt) if r.x0 is None
                else np.asarray(r.x0, dtype=dt)
                for r in reqs
            ])
        values, rhs, tols, x0, _ = bucketing.pad_lanes(
            values, rhs, tols, bkt, x0=x0
        )
        maxiter = max(
            (r.maxiter if r.maxiter is not None else pattern.shape[0] * 10)
            for r in reqs
        )
        snap = plan_cache.snapshot()
        faulty = _faults.ACTIVE and _faults.targets("matvec")
        key = f"batch.{solver}.B{bkt}.{np.dtype(dt).str}{plan.key_suffix}"
        if faulty:
            # fault-wrapped programs carry the injection callback in
            # their trace: never share cache entries with clean ones
            key += ".faults"
        args = (
            jnp.asarray(values), jnp.asarray(rhs), jnp.asarray(x0),
            jnp.asarray(tols), maxiter,
        )
        t_packed = time.monotonic()
        built: dict = {}

        def build():
            # a cache miss builds AND attributes: pack/trace wall-clock,
            # AOT compile duration, XLA cost/memory analysis — one
            # plan_cache.compile event per program, ever (same cadence
            # as the miss itself)
            tb = time.perf_counter()
            fn = self._build_program(pattern, bkt, np.dtype(dt),
                                     solver=solver, plan=plan)
            prog, info = _cost.attribute(
                key, fn, args,
                pack_s=time.perf_counter() - tb,
                solver=solver, bucket=bkt, dtype=np.dtype(dt).str,
                n=pattern.shape[0], nnz=pattern.nnz,
            )
            built.update(info)
            return prog

        try:
            prog = plan_cache.get(pattern, key, build)
            if built and not faulty:
                # a freshly built bucket program is warm-start state:
                # note it (and its pattern artifact) in the vault
                # manifest so a restarted process replays it. Fault-
                # wrapped programs are never noted — their traces carry
                # the injection callback.
                from .. import vault

                if vault.enabled() and plan.strategy != "row":
                    # row programs are rebuilt per dispatch (no compiled
                    # artifact worth replaying); batch-sharded programs
                    # note the mesh fingerprint so only a same-topology
                    # restart replays them
                    vault.note_program(
                        pattern, solver=solver, bucket=bkt,
                        dtype=np.dtype(dt).str,
                        mesh=(plan.fingerprint if plan.sharded else None),
                        strategy=(plan.strategy if plan.sharded else None),
                    )
            # sampled timed dispatch (ISSUE 12): every Nth dispatch
            # takes ONE extra timestamp at the dispatch-return boundary
            # so the solve wall clock splits into host (async dispatch)
            # vs device (block_until_ready wait) time. Off (the
            # default) takes no timestamp at all; the program and its
            # plan-cache key are identical either way.
            self._dispatch_seq += 1
            sampled = (
                self.profile_every > 0
                and self._dispatch_seq % self.profile_every == 0
            )
            t_solve0 = time.monotonic()
            out = prog(*args)
            t_dispatched = time.monotonic() if sampled else None
            try:
                jax.block_until_ready(out)
            except Exception:
                pass  # non-jax leaves (ints) — np.asarray blocks below
            t_solved = time.monotonic()
            X, iters, resid2, conv = out
            X = np.asarray(X)
            iters = np.asarray(iters)
            resid2 = np.asarray(resid2)
            conv = np.asarray(conv)
        except Exception as e:  # noqa: BLE001 - degrade, don't strand
            # Graceful degradation (ISSUE 5): the compiled batched path
            # is unavailable (Pallas lowering gone mid-session, plan
            # cache failure, injected program fault) — solve the lanes
            # one by one on the eager path instead of failing the bucket.
            _DEGRADED.inc()
            if telemetry.enabled():
                telemetry.record(
                    "batch.degraded", solver=solver, reason=repr(e)[:200],
                    lanes=nb,
                )
            self._solve_degraded(reqs, dt, solver)
            return
        t_read = time.monotonic()
        profile_ms = None
        if sampled:
            profile_ms = (
                max((t_dispatched - t_solve0) * 1e3, 0.0),  # host
                max((t_solved - t_dispatched) * 1e3, 0.0),  # device
            )
            _profiler.record_device_sample(key, *profile_ms)
        requeue_lanes = []
        for i, r in enumerate(reqs):
            r.ticket._offer(X[i], iters[i], resid2[i], conv[i],
                            solver=solver)
            if (
                allow_requeue and self.requeue and not r.ticket.requeued
                and (not conv[i] or not np.isfinite(resid2[i]))
            ):
                r.ticket.requeued = True
                requeue_lanes.append(r)
        self.dispatches += 1
        _DISPATCHES.inc()
        # occupancy/waste count against the FINAL bucket (incl. any
        # mesh-multiple rounding); pad lanes are excluded by construction
        _BUCKET_OCCUPANCY.observe(nb / bkt)
        _PAD_WASTE.inc(bkt - nb)
        self._fleet_account(
            plan, solver, dt, nb, bkt, iters, max(t_solved - t_solve0, 0.0)
        )
        if telemetry.enabled():
            # bucket-level phase wall clocks, accumulated onto each
            # lane's ticket (a requeued lane sums both dispatches).
            # compile_ms is the build's share (pattern pack + AOT
            # compile), which ran inside plan_cache.get — i.e. between
            # t_packed and t_solve0 — so the phases stay disjoint
            compile_ms = (
                built.get("compile_s", 0.0) + built.get("pack_s", 0.0)
            ) * 1e3
            pack_ms = max((t_packed - t0) * 1e3, 0.0)
            solve_ms = max((t_solved - t_solve0) * 1e3, 0.0)
            readback_ms = max((t_read - t_solved) * 1e3, 0.0)
            for r in reqs:
                ph = r.ticket.phase_ms
                # queue wait accrues from submit (first dispatch) or
                # from the end of the previously accounted dispatch (a
                # requeue) — the phases of a requeued ticket then tile
                # its latency instead of double-counting the first pass
                base = (
                    r.ticket.t_mark if r.ticket.t_mark is not None
                    else r.ticket.t_submit
                )
                ph["queue_ms"] = ph.get("queue_ms", 0.0) + max(
                    (t0 - base) * 1e3, 0.0
                )
                ph["pack_ms"] = ph.get("pack_ms", 0.0) + pack_ms
                ph["compile_ms"] = ph.get("compile_ms", 0.0) + compile_ms
                ph["solve_ms"] = ph.get("solve_ms", 0.0) + solve_ms
                ph["readback_ms"] = ph.get("readback_ms", 0.0) + readback_ms
                r.ticket.t_mark = t_read
            q_ms = [
                (t0 - r.ticket.t_submit) * 1e3 for r in reqs
            ]
            cache_d = plan_cache.delta(snap)
            telemetry.record(
                "batch.dispatch", solver=solver, batch=nb,
                bucket=bkt, pad_waste=bkt - nb,
                queue_ms_max=round(max(q_ms), 3),
                queue_ms_mean=round(sum(q_ms) / len(q_ms), 3),
                dispatch_ms=round((time.monotonic() - t0) * 1e3, 3),
                solve_ms=round(solve_ms, 3),
                compile_ms=round(compile_ms, 3),
                program=key,
                iters_max=int(iters[:nb].max(initial=0)),
                iters_mean=float(iters[:nb].mean()) if nb else 0.0,
                plan_cache=cache_d,
                n=pattern.shape[0], nnz=pattern.nnz,
                strategy=plan.strategy, S=plan.S,
                # measured host/device split, sampled dispatches only
                # (the axon_report programs table's device_ms column)
                **({"host_ms": round(profile_ms[0], 3),
                    "device_ms": round(profile_ms[1], 3)}
                   if profile_ms is not None else {}),
            )
        if requeue_lanes:
            self._requeue(requeue_lanes, dt)

    # -- resilience paths --------------------------------------------------
    def _requeue(self, reqs, dt) -> None:
        """Failed-lane requeue: one fallback bucket under the safer
        solver/dtype; the fallback result only replaces a lane's first
        result when it is better (``SolveTicket._offer``)."""
        fb_dt = _promote(dt)
        _REQUEUES.inc(len(reqs))
        if telemetry.enabled():
            # explicit tickets: the enclosing dispatch scope covers the
            # WHOLE original bucket, this event is about the requeued
            # lanes only
            telemetry.record(
                "batch.requeue", solver=self.fallback_solver,
                lanes=len(reqs), from_solver=self.solver,
                dtype=np.dtype(fb_dt).str,
                tickets=[r.ticket.id for r in reqs],
            )
        # fresh maxiter budget: the lane may have failed BECAUSE the
        # caller's budget was too small for the requested solver
        fb = [
            _Request(r.pattern, r.values, r.b, r.tol, None, None, r.ticket)
            for r in reqs
        ]
        try:
            self._dispatch(fb, fb_dt, solver=self.fallback_solver,
                           allow_requeue=False)
        except Exception:  # noqa: BLE001 - first results already stand
            # the requeue is best-effort: every lane already holds its
            # first (unconverged) result, which result() returns
            pass

    def _solve_degraded(self, reqs, dt, solver: str) -> None:
        """Per-lane eager fallback when the compiled bucket program is
        unavailable: each lane solves through the plain linalg drivers
        over a csr view of the pattern; per-lane failures fail only that
        lane's ticket."""
        from ..utils import asjnp

        pattern = reqs[0].pattern
        indices = asjnp(pattern.indices)
        indptr = asjnp(pattern.indptr)
        for r in reqs:
            # narrow the trace context to the one lane being solved so
            # the eager solvers' events attribute per request
            with telemetry.ticket_scope(r.ticket.id):
                self._solve_degraded_lane(
                    r, dt, solver, indices, indptr, pattern
                )

    def _solve_degraded_lane(self, r, dt, solver, indices, indptr,
                             pattern) -> None:
        from .. import linalg
        from ..csr import csr_array
        from ..utils import asjnp

        try:
            A = csr_array.from_parts(
                asjnp(r.values.astype(dt)), indices, indptr,
                pattern.shape,
            )
            b = asjnp(r.b.astype(dt))
            maxiter = (
                r.maxiter if r.maxiter is not None
                else pattern.shape[0] * 10
            )
            if solver == "gmres":
                x, iters = linalg.gmres(
                    A, b, tol=0.0, atol=r.tol, restart=self.restart
                )
            elif solver == "bicgstab":
                x, iters = linalg.bicgstab(
                    A, b, tol=r.tol, maxiter=maxiter
                )
            else:
                x, iters = linalg.cg(A, b, tol=r.tol, maxiter=maxiter)
            resid2 = float(
                np.linalg.norm(r.b - np.asarray(A @ asjnp(np.asarray(x))))
                ** 2
            )
            r.ticket._offer(
                np.asarray(x), iters, resid2,
                np.isfinite(resid2) and resid2 <= r.tol ** 2,
                solver=solver,
            )
        except Exception as e:  # noqa: BLE001 - lane isolation
            r.ticket._fail(e)

    def _build_program(self, pattern: SparsityPattern, bkt: int, dt,
                       solver: str | None = None, plan=None):
        """The per-bucket compiled program: pattern pack + masked solver
        loop under ONE ``jax.jit`` whose arguments are the value stack,
        rhs, x0 and tolerances — so same-bucket dispatches with fresh
        coefficients reuse the executable (no constants captured from
        any particular batch).

        ``plan`` routes the fleet strategies (ISSUE 10): 'batch' wraps
        the SAME loop cores in a ``shard_map`` over the mesh batch axis
        with the psum all-converged exit (gmres shards its inputs and
        lets GSPMD partition the host-driven cycle), 'row' wraps
        ``DistCSR``/``dist_cg`` in a B=1 bucket signature. 'single' (or
        ``None``) is byte-identical to the classic path."""
        solver = solver or self.solver
        if plan is not None and plan.strategy == "row":
            return fleet_mod.build_row_program(
                pattern, dt, plan.mesh,
                conv_test_iters=self.conv_test_iters,
            )
        if plan is not None and plan.strategy == "batch":
            return fleet_mod.build_batch_program(
                pattern, bkt, dt, solver, plan.mesh,
                self.conv_test_iters,
                gmres_inner=(
                    self._build_gmres_program(pattern, bkt, dt)
                    if solver == "gmres" else None
                ),
            )
        if solver == "gmres":
            return self._build_gmres_program(pattern, bkt, dt)
        pack = pattern.sell_pack()
        idx_slabs, pos, zero_rows = (
            pack.idx_slabs, pack.pos, pack.plan.zero_rows
        )
        loop = (
            krylov._cg_loop if solver == "cg"
            else krylov._bicgstab_loop
        )
        cti = self.conv_test_iters

        @jax.jit
        def run(values, rhs, x0, tols, maxiter):
            vals = pack.pack_values(values)

            def mv(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals, pos, X, zero_rows
                )

            return loop(krylov._maybe_faulty_mv(mv), rhs, x0, tols,
                        maxiter, cti)

        return run

    def _build_gmres_program(self, pattern, bkt, dt):
        """GMRES keeps its host-driven outer restart loop, so the bucket
        'program' is a closure dispatching :func:`krylov.batched_gmres`
        over a pattern-packed operator — restart cycles still compile
        once per bucket (the jitted cycle is rebuilt per dispatch; the
        XLA executable comes from jax's compile cache)."""
        restart = self.restart

        restart_eff = restart or min(20, pattern.shape[0])

        def run(values, rhs, x0, tols, maxiter):
            op = BatchedCSR(pattern, values)
            # batched_gmres takes a scalar-or-(B,) relative tol; the
            # session's per-lane ABSOLUTE targets ride the atol floor.
            # Its maxiter counts OUTER restarts; bound inner work by the
            # session's maxiter contract.
            outer = max(-(-int(maxiter) // restart_eff), 1)
            X, info = krylov.batched_gmres(
                op, rhs, x0=x0, tol=0.0, atol=tols, restart=restart_eff,
                maxiter=outer,
            )
            return X, info.iters, info.resid2, info.converged

        return run
