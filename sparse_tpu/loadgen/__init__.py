"""sparse_tpu.loadgen — deterministic traffic generation + load reports.

The active half of the Axon observability stack (ISSUE 11): where
:mod:`sparse_tpu.telemetry` makes a serving session *explainable*, this
package makes it *measurable under load* — the sustained-throughput
question ("how many req/s can this session hold at its p95 SLO?") the
passive instrumentation cannot answer by itself. Legate Sparse ships a
task-level profiler for exactly this reason (PAPERS.md §1), and
Ginkgo's batched work reports throughput-under-load, not single-solve
latency, as the headline (PAPERS.md §2).

Two pieces:

* :class:`ArrivalTrace` (:mod:`._trace`) — seeded, virtual-clock
  request schedules: Poisson / bursty / uniform / closed-loop clauses,
  multi-tenant mixes with fairness weights, a strict spec grammar
  (``"poisson:rate=100,duration=2,seed=0,tenant=a;burst:..."``). No
  wall-clock randomness anywhere — the same spec replays bit-identically.
* :func:`run_load` (:mod:`._run`) — pace a trace onto a live
  :class:`~sparse_tpu.batch.service.SolveSession` through its real
  ticket path and produce a :class:`LoadReport`: offered vs achieved
  req/s, p50/p95/p99 ticket latency, SLO-miss rate, queue-depth and
  device-occupancy time series sampled from the always-on metrics
  registry, and a weighted per-tenant fairness index
  (:func:`fairness_index`).

``bench.py``'s ``sustained_cg`` row and ``scripts/chaos_check.py``
scenario 8 (loadgen + watchdog alerting under fault injection) are the
CI consumers; docs/telemetry.md "Axon v5" documents the trace grammar
and the report fields.
"""

from __future__ import annotations

from ._run import (  # noqa: F401
    LoadReport,
    build_report,
    fairness_index,
    run_load,
)
from ._trace import (  # noqa: F401
    Arrival,
    ArrivalTrace,
    ClosedClause,
    LoadSpecError,
)

__all__ = [
    "Arrival",
    "ArrivalTrace",
    "ClosedClause",
    "LoadReport",
    "LoadSpecError",
    "build_report",
    "fairness_index",
    "run_load",
]
