"""Arrival traces: deterministic, seeded request schedules.

A load test is only evidence if it is reproducible, so every schedule
here is built from a **virtual clock** and a seeded ``numpy`` Generator
— no wall-clock randomness anywhere in the library. The same spec +
seed produces the same arrival times, tenants and ordering on every
machine, every run (``tests/test_loadgen.py`` pins it); the *runner*
(:mod:`._run`) is the only place virtual time meets ``time.monotonic``.

Spec grammar (mirrors the ``SPARSE_TPU_FAULTS`` clause style —
``;``-separated clauses, ``key=value`` options, loud errors on typos)::

    pattern:key=value[,key=value...][;pattern:...]

    poisson:rate=100,duration=2,seed=0          # exponential gaps
    burst:rate=20,burst_rate=400,period=1,duty=0.25,duration=2,seed=0
    uniform:rate=50,duration=2                  # evenly spaced
    closed:concurrency=4,requests=64            # completion-driven
    ingest:rate=2,duration=2,seed=0,size=48     # unseen-pattern arrivals
    remesh:at=1.0,to=4                          # topology change mid-trace

Every timed clause accepts ``tenant=`` (a label stamped onto each
request — the fairness dimension) and ``weight=`` (the tenant's fair
share weight, default 1). Multiple clauses merge into one trace sorted
by virtual time — a mixed-pattern multi-tenant schedule is just
``poisson:...,tenant=a;burst:...,tenant=b``. ``closed`` clauses have no
virtual timeline (the next arrival is the previous completion); the
runner executes them after the timed phase.

``ingest`` clauses (ISSUE 18) schedule *unseen-pattern matrix
arrivals* riding the same Poisson process: each arrival carries
``kind='ingest'`` and a ``size`` profile (matrix dimension) instead of
a solve, and the runner routes it through
``SolveSession.ingest`` — so one trace mixes serving traffic with the
onboarding traffic that must never disturb it. Ingest arrivals are
excluded from the solve latency/fairness rollups; their onboarding
latency percentiles report separately (``LoadReport.onboard``).

``remesh`` clauses (ISSUE 20) schedule a *topology change* at a fixed
virtual time: the arrival carries ``kind='remesh'`` and the runner
routes it through ``SolveSession.remesh`` (``to=N`` forges an
``N``-device target mesh; ``to=0`` re-resolves the live default) —
so one trace drives serving traffic ACROSS a mesh shrink/regain, the
elastic-survival shape ``scripts/chaos_check.py`` scenario 16 pins.
Remesh arrivals never count toward the solve offered rate.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

__all__ = [
    "Arrival",
    "ArrivalTrace",
    "ClosedClause",
    "LoadSpecError",
]


class LoadSpecError(ValueError):
    """A trace spec clause that does not parse/validate (a typo'd load
    test must fail loudly, not quietly offer the wrong traffic)."""


@dataclass(frozen=True)
class Arrival:
    """One scheduled request: virtual arrival time (seconds from trace
    start) and the tenant label it carries ('' = the default tenant).
    ``kind`` is ``'solve'`` (classic), ``'ingest'`` (an unseen-pattern
    matrix arrival, ISSUE 18) or ``'remesh'`` (a scheduled topology
    change, ISSUE 20); ``size`` is the ingest clause's matrix-dimension
    profile or the remesh clause's target device count (0 for
    solves)."""

    t: float
    tenant: str = ""
    kind: str = "solve"
    size: int = 0


@dataclass(frozen=True)
class ClosedClause:
    """A closed-loop traffic source: keep ``concurrency`` requests in
    flight until ``requests`` have completed (arrivals are driven by
    completions, not a clock — the saturation-throughput shape)."""

    concurrency: int
    requests: int
    tenant: str = ""


class ArrivalTrace:
    """An immutable request schedule: sorted timed arrivals + closed
    clauses + per-tenant fairness weights. Build via the classmethods
    (:meth:`poisson`, :meth:`bursty`, :meth:`uniform`,
    :meth:`closed_loop`), :meth:`parse`, or ``+`` (merge)."""

    __slots__ = ("arrivals", "duration", "closed", "weights", "spec")

    def __init__(self, arrivals=(), duration: float = 0.0, closed=(),
                 weights=None, spec: str = ""):
        self.arrivals = tuple(
            sorted(arrivals, key=lambda a: (a.t, a.tenant))
        )
        self.duration = float(duration)
        self.closed = tuple(closed)
        self.weights = dict(weights or {})
        self.spec = spec

    # -- builders ----------------------------------------------------------
    @classmethod
    def poisson(cls, rate: float, duration: float, seed: int = 0,
                tenant: str = "", weight: float = 1.0) -> "ArrivalTrace":
        """Poisson arrivals at ``rate`` req/s over ``duration`` virtual
        seconds (i.i.d. exponential gaps from the seeded generator)."""
        _check_rate(rate, duration)
        rng = np.random.default_rng(seed)
        times = []
        t = float(rng.exponential(1.0 / rate))
        while t < duration:
            times.append(t)
            t += float(rng.exponential(1.0 / rate))
        spec = _clause("poisson", rate=rate, duration=duration, seed=seed,
                       tenant=tenant, weight=weight)
        return cls([Arrival(t, tenant) for t in times], duration,
                   weights={tenant: float(weight)}, spec=spec)

    @classmethod
    def bursty(cls, rate: float, burst_rate: float, period: float,
               duty: float, duration: float, seed: int = 0,
               tenant: str = "", weight: float = 1.0) -> "ArrivalTrace":
        """Piecewise-Poisson bursts: ``burst_rate`` during the first
        ``duty`` fraction of every ``period``-second window, the base
        ``rate`` otherwise — the flash-crowd shape a p95 SLO actually
        meets in production."""
        _check_rate(rate, duration)
        if not (burst_rate > 0):
            raise LoadSpecError(f"burst_rate={burst_rate} must be > 0")
        if not (period > 0):
            raise LoadSpecError(f"period={period} must be > 0")
        if not (0.0 < duty < 1.0):
            raise LoadSpecError(f"duty={duty} outside (0, 1)")
        rng = np.random.default_rng(seed)
        times = []
        # window edges in virtual time; each sub-interval is Poisson at
        # its own rate, gaps drawn in order so the schedule is one
        # deterministic stream
        edges = [0.0]
        t = 0.0
        while t < duration:
            t += period * duty
            edges.append(min(t, duration))
            t = min(t + period * (1.0 - duty), duration + period)
            edges.append(min(t, duration))
        for i in range(len(edges) - 1):
            a, b = edges[i], edges[i + 1]
            if b <= a:
                continue
            r = burst_rate if i % 2 == 0 else rate
            t = a + float(rng.exponential(1.0 / r))
            while t < b:
                times.append(t)
                t += float(rng.exponential(1.0 / r))
        spec = _clause("burst", rate=rate, burst_rate=burst_rate,
                       period=period, duty=duty, duration=duration,
                       seed=seed, tenant=tenant, weight=weight)
        return cls([Arrival(t, tenant) for t in times], duration,
                   weights={tenant: float(weight)}, spec=spec)

    @classmethod
    def uniform(cls, rate: float, duration: float, tenant: str = "",
                weight: float = 1.0) -> "ArrivalTrace":
        """Evenly spaced arrivals (no randomness at all): the baseline
        schedule for isolating queueing effects from arrival noise."""
        _check_rate(rate, duration)
        gap = 1.0 / rate
        times = []
        k = 1
        while k * gap < duration:
            times.append(k * gap)
            k += 1
        spec = _clause("uniform", rate=rate, duration=duration,
                       tenant=tenant, weight=weight)
        return cls([Arrival(t, tenant) for t in times], duration,
                   weights={tenant: float(weight)}, spec=spec)

    @classmethod
    def ingest_arrivals(cls, rate: float, duration: float, seed: int = 0,
                        size: int = 48, tenant: str = "ingest",
                        weight: float = 1.0) -> "ArrivalTrace":
        """Unseen-pattern matrix arrivals (ISSUE 18): Poisson at
        ``rate`` arrivals/s over ``duration`` virtual seconds, each
        arrival an ``kind='ingest'`` event sized by the ``size``
        profile (matrix dimension). The runner materializes every
        arrival as a DISTINCT seeded matrix structure — the unseen-
        pattern stream the onboarding pipeline must absorb without
        disturbing the solve p95."""
        _check_rate(rate, duration)
        if int(size) < 2:
            raise LoadSpecError(f"size={size} must be >= 2")
        rng = np.random.default_rng(seed)
        times = []
        t = float(rng.exponential(1.0 / rate))
        while t < duration:
            times.append(t)
            t += float(rng.exponential(1.0 / rate))
        spec = _clause("ingest", rate=rate, duration=duration, seed=seed,
                       size=int(size), tenant=tenant, weight=weight)
        return cls(
            [Arrival(t, tenant, kind="ingest", size=int(size))
             for t in times],
            duration, weights={tenant: float(weight)}, spec=spec,
        )

    @classmethod
    def remesh_at(cls, at: float, to: int = 0) -> "ArrivalTrace":
        """A scheduled topology change (ISSUE 20): one ``kind='remesh'``
        arrival at virtual time ``at``, targeting a forged ``to``-device
        mesh (``to=0`` re-resolves the live default). Merge with a timed
        traffic clause to shrink/regain the fleet mid-trace."""
        if not (at > 0):
            raise LoadSpecError(f"at={at} must be > 0")
        if int(to) < 0:
            raise LoadSpecError(f"to={to} must be >= 0")
        spec = _clause("remesh", at=at, **({"to": int(to)} if to else {}))
        return cls([Arrival(float(at), "", kind="remesh", size=int(to))],
                   float(at), spec=spec)

    @classmethod
    def closed_loop(cls, concurrency: int, requests: int,
                    tenant: str = "", weight: float = 1.0) -> "ArrivalTrace":
        """Closed-loop source: ``concurrency`` in flight until
        ``requests`` complete (no virtual timeline)."""
        if int(concurrency) < 1 or int(requests) < 1:
            raise LoadSpecError(
                f"closed loop needs concurrency >= 1 and requests >= 1 "
                f"(got {concurrency}, {requests})"
            )
        spec = _clause("closed", concurrency=int(concurrency),
                       requests=int(requests), tenant=tenant, weight=weight)
        return cls([], 0.0,
                   closed=[ClosedClause(int(concurrency), int(requests),
                                        tenant)],
                   weights={tenant: float(weight)}, spec=spec)

    # -- combination -------------------------------------------------------
    def __add__(self, other: "ArrivalTrace") -> "ArrivalTrace":
        if not isinstance(other, ArrivalTrace):
            return NotImplemented
        weights = dict(self.weights)
        weights.update(other.weights)
        spec = ";".join(s for s in (self.spec, other.spec) if s)
        return ArrivalTrace(
            self.arrivals + other.arrivals,
            max(self.duration, other.duration),
            closed=self.closed + other.closed,
            weights=weights, spec=spec,
        )

    # -- parsing -----------------------------------------------------------
    @classmethod
    def parse(cls, spec: str) -> "ArrivalTrace":
        """Build a trace from the spec grammar (module docstring).
        Raises :class:`LoadSpecError` on unknown patterns/keys or
        malformed values."""
        trace = None
        for raw in str(spec).split(";"):
            raw = raw.strip()
            if not raw:
                continue
            head, _, opts = raw.partition(":")
            pattern = head.strip().lower()
            if pattern not in _PATTERNS:
                raise LoadSpecError(
                    f"clause {raw!r}: unknown pattern {pattern!r} "
                    f"(one of {sorted(_PATTERNS)})"
                )
            builder, keys = _PATTERNS[pattern]
            kw: dict = {}
            for opt in opts.split(","):
                opt = opt.strip()
                if not opt:
                    continue
                if "=" not in opt:
                    raise LoadSpecError(
                        f"clause {raw!r}: option {opt!r} is not key=value"
                    )
                k, v = (s.strip() for s in opt.split("=", 1))
                if k not in keys:
                    raise LoadSpecError(
                        f"clause {raw!r}: unknown key {k!r} for "
                        f"{pattern!r} (one of {sorted(keys)})"
                    )
                try:
                    kw[k] = keys[k](v)
                except ValueError as e:
                    raise LoadSpecError(
                        f"clause {raw!r}: bad value for {k!r}: {v!r}"
                    ) from e
            try:
                piece = builder(**kw)
            except TypeError as e:
                raise LoadSpecError(f"clause {raw!r}: {e}") from None
            trace = piece if trace is None else trace + piece
        if trace is None:
            raise LoadSpecError(f"empty trace spec {spec!r}")
        return trace

    def describe(self) -> str:
        """The canonical spec string (re-parses to an equal trace)."""
        return self.spec

    # -- views -------------------------------------------------------------
    def arrival_times(self) -> np.ndarray:
        return np.asarray([a.t for a in self.arrivals], dtype=np.float64)

    def tenants(self) -> list:
        seen = {a.tenant for a in self.arrivals}
        seen.update(c.tenant for c in self.closed)
        return sorted(seen)

    def counts(self) -> dict:
        """Scheduled requests per tenant (timed + closed)."""
        out: dict = {}
        for a in self.arrivals:
            out[a.tenant] = out.get(a.tenant, 0) + 1
        for c in self.closed:
            out[c.tenant] = out.get(c.tenant, 0) + c.requests
        return out

    def __len__(self) -> int:
        return len(self.arrivals) + sum(c.requests for c in self.closed)

    @property
    def offered_rps(self) -> float:
        """Timed offered rate in *virtual* req/s (0 for pure closed-loop
        traces — their offered rate is whatever completes)."""
        if self.duration <= 0 or not self.arrivals:
            return 0.0
        return len(self.arrivals) / self.duration

    def __repr__(self) -> str:
        return (
            f"ArrivalTrace({len(self.arrivals)} timed"
            + (f" + {sum(c.requests for c in self.closed)} closed"
               if self.closed else "")
            + f", duration={self.duration:g}s, "
            f"tenants={self.tenants()})"
        )


def _check_rate(rate, duration) -> None:
    if not (rate > 0):
        raise LoadSpecError(f"rate={rate} must be > 0")
    if not (duration > 0):
        raise LoadSpecError(f"duration={duration} must be > 0")


def _clause(pattern: str, **kw) -> str:
    parts = []
    for k, v in kw.items():
        if k == "tenant" and not v:
            continue
        if k == "weight" and float(v) == 1.0:
            continue
        parts.append(f"{k}={v:g}" if isinstance(v, float) else f"{k}={v}")
    return f"{pattern}:" + ",".join(parts)


#: pattern -> (builder, {key: coercion}) for :meth:`ArrivalTrace.parse`
_PATTERNS = {
    "poisson": (ArrivalTrace.poisson, {
        "rate": float, "duration": float, "seed": int,
        "tenant": str, "weight": float,
    }),
    "burst": (ArrivalTrace.bursty, {
        "rate": float, "burst_rate": float, "period": float, "duty": float,
        "duration": float, "seed": int, "tenant": str, "weight": float,
    }),
    "uniform": (ArrivalTrace.uniform, {
        "rate": float, "duration": float, "tenant": str, "weight": float,
    }),
    "closed": (ArrivalTrace.closed_loop, {
        "concurrency": int, "requests": int, "tenant": str, "weight": float,
    }),
    "ingest": (ArrivalTrace.ingest_arrivals, {
        "rate": float, "duration": float, "seed": int, "size": int,
        "tenant": str, "weight": float,
    }),
    "remesh": (ArrivalTrace.remesh_at, {"at": float, "to": int}),
}
