"""The load runner: drive a ``SolveSession`` through an arrival trace.

This is the only module in :mod:`sparse_tpu.loadgen` that touches the
wall clock. :func:`run_load` paces the trace's virtual arrival times
onto ``time.monotonic`` (scaled by ``time_scale``), submits every
request through the session's REAL ticket path (``submit`` → queue →
coalesce → bucketed dispatch → terminal resolution, tenant label and
all), and assembles a :class:`LoadReport`:

* **offered vs achieved req/s** — what the trace asked for vs what the
  session completed per wall second;
* **latency percentiles** — p50/p95/p99/max/mean end-to-end ticket
  latency (submit → resolved, the same number the ``batch.ticket``
  terminal events and the always-on ``batch.ticket_latency`` histogram
  carry);
* **SLO-miss rate** — per-ticket latency against the session's
  ``slo_ms`` objective;
* **queue-depth / device-occupancy time series** — sampled from the
  always-on metrics registry (``batch.queue_depth``,
  ``fleet.device_occupancy``) while the trace plays, bounded by
  decimation so a long run cannot grow without bound;
* **per-tenant fairness** — a weighted Jain index over achieved
  per-tenant throughput shares (:func:`fairness_index`);
* **achieved in-flight depth** — with the streaming pipeline
  (ISSUE 13) the runner drives the session's future API: timed-phase
  flushes are non-blocking on a pipelined session (``inflight > 1``),
  the closed-loop phase keeps ``concurrency`` tickets genuinely
  outstanding and awaits them through ``SolveTicket.result()``, and
  the report records the max/mean unresolved-ticket depth observed at
  each await — the honesty check that closed-loop concurrency > 1
  really overlapped.

Report construction is a pure function (:func:`build_report`) over the
collected outcomes, so the rollup math is unit-testable without a
session or a clock. With telemetry enabled, a completed run emits one
``loadgen.trace`` event carrying the trace spec and the headline
numbers — the record ``scripts/axon_report.py``'s ``load`` rollup
reads.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

from ..telemetry import _metrics, _recorder
from ._trace import ArrivalTrace

__all__ = ["LoadReport", "build_report", "fairness_index", "run_load"]

#: hard cap on the sampled time series; hitting it decimates 2:1 and
#: doubles the sampling period (bounded memory for arbitrarily long runs)
_SAMPLE_CAP = 2048


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile (same convention as axon_report)."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


def fairness_index(shares: dict) -> float:
    """Jain's fairness index over weighted shares
    ``x_i = achieved_i / weight_i``: ``(Σx)² / (n·Σx²)`` ∈ (0, 1], 1 =
    every tenant got throughput proportional to its weight. Empty or
    all-zero shares read as perfectly fair (nothing was contested)."""
    xs = [float(v) for v in shares.values()]
    n = len(xs)
    if n == 0:
        return 1.0
    s, s2 = sum(xs), sum(x * x for x in xs)
    if s2 <= 0.0:
        return 1.0
    return (s * s) / (n * s2)


@dataclass
class LoadReport:
    """The result of one load run (JSON-friendly via :meth:`as_dict`)."""

    trace: str
    arrivals: int
    completed: int
    failed: int
    wall_s: float
    offered_rps: float
    achieved_rps: float
    latency_ms: dict
    slo_ms: float | None
    slo_misses: int
    slo_miss_rate: float
    tenants: dict
    fairness: float
    queue_depth: list = field(default_factory=list)
    device_occupancy: list = field(default_factory=list)
    dispatches: int = 0
    requeued: int = 0
    #: achieved in-flight (submitted-but-unresolved ticket) depth,
    #: sampled at timed-phase flushes and at every closed-loop await:
    #: {"max": int, "mean": float, "pipelined": bool} — empty when the
    #: run had no observation points
    inflight_depth: dict = field(default_factory=dict)
    #: background-onboarding rollup for traces with ``ingest`` clauses
    #: (ISSUE 18): arrival count, dedup hits, failures and onboarding
    #: latency percentiles — reported SEPARATELY from the solve
    #: latency_ms so onboarding cost can never masquerade as (or hide
    #: in) the serving p95. Empty when the trace had no ingest arrivals.
    onboard: dict = field(default_factory=dict)
    #: executed topology transitions for traces with ``remesh`` clauses
    #: (ISSUE 20): outcome -> count (``'ok'``/``'noop'``/``'latched'``/
    #: ``'error'``). Empty when the trace had no remesh arrivals.
    remeshes: dict = field(default_factory=dict)

    def as_dict(self) -> dict:
        return {
            "trace": self.trace,
            "arrivals": self.arrivals,
            "completed": self.completed,
            "failed": self.failed,
            "wall_s": self.wall_s,
            "offered_rps": self.offered_rps,
            "achieved_rps": self.achieved_rps,
            "latency_ms": dict(self.latency_ms),
            "slo_ms": self.slo_ms,
            "slo_misses": self.slo_misses,
            "slo_miss_rate": self.slo_miss_rate,
            "tenants": {k: dict(v) for k, v in self.tenants.items()},
            "fairness": self.fairness,
            "queue_depth": list(self.queue_depth),
            "device_occupancy": list(self.device_occupancy),
            "dispatches": self.dispatches,
            "requeued": self.requeued,
            "inflight_depth": dict(self.inflight_depth),
            "onboard": dict(self.onboard),
            "remeshes": dict(self.remeshes),
        }


def build_report(trace: ArrivalTrace, outcomes, wall_s: float,
                 slo_ms=None, *, time_scale: float = 1.0,
                 queue_depth=(), device_occupancy=(),
                 dispatches: int = 0,
                 inflight_depth: dict | None = None,
                 onboard=(), onboard_rejected: int = 0,
                 remeshes=()) -> LoadReport:
    """Pure rollup of a run: ``outcomes`` is a sequence of
    ``(tenant, latency_s, ok, requeued)`` tuples (what the runner
    collected from the resolved tickets), ``onboard`` a sequence of
    ``(wall_ms, ok, dedup)`` tuples from the resolved ingest tickets
    (``onboard_rejected`` counts admission-rejected submissions that
    never got a ticket). Deterministic for deterministic inputs — the
    trace spec, counts, per-tenant shares and the fairness index never
    depend on the clock."""
    wall_s = max(float(wall_s), 1e-9)
    lats = sorted(o[1] * 1e3 for o in outcomes if o[2])
    completed = sum(1 for o in outcomes if o[2])
    failed = len(outcomes) - completed
    requeued = sum(1 for o in outcomes if o[3])
    misses = 0
    if slo_ms is not None:
        misses = sum(
            1 for o in outcomes if o[2] and o[1] * 1e3 > float(slo_ms)
        )
    per_tenant: dict = {}
    for tenant, lat_s, ok, _rq in outcomes:
        t = per_tenant.setdefault(str(tenant), {
            "arrivals": 0, "completed": 0, "achieved_rps": 0.0,
            "weight": float(trace.weights.get(tenant, 1.0)),
        })
        t["arrivals"] += 1
        if ok:
            t["completed"] += 1
    shares = {}
    for tenant, t in per_tenant.items():
        t["achieved_rps"] = round(t["completed"] / wall_s, 3)
        shares[tenant] = t["completed"] / max(t["weight"], 1e-12)
    onb: dict = {}
    onboard = list(onboard)
    if onboard or onboard_rejected:
        olats = sorted(w for w, ok, _d in onboard if ok and w is not None)
        ocomp = sum(1 for _w, ok, _d in onboard if ok)
        onb = {
            "arrivals": len(onboard) + int(onboard_rejected),
            "completed": ocomp,
            "failed": len(onboard) - ocomp + int(onboard_rejected),
            "dedup_hits": sum(1 for _w, ok, d in onboard if ok and d),
            "latency_ms": {
                "p50": round(_percentile(olats, 0.50), 3),
                "p95": round(_percentile(olats, 0.95), 3),
                "p99": round(_percentile(olats, 0.99), 3),
                "max": round(olats[-1], 3) if olats else 0.0,
                "mean": round(sum(olats) / len(olats), 3) if olats else 0.0,
            },
        }
    rmsh: dict = {}
    for outcome in remeshes:
        o = str(outcome)
        rmsh[o] = rmsh.get(o, 0) + 1
    # offered = the trace's virtual rate mapped to the wall (a pure
    # closed-loop trace has no timed rate: offered == achieved);
    # non-solve arrivals (ingest onboarding, remesh transitions) never
    # count toward the solve offered/achieved rates
    solve_arrivals = sum(
        1 for a in trace.arrivals if getattr(a, "kind", "solve") == "solve"
    )
    if trace.duration > 0 and solve_arrivals:
        offered = solve_arrivals / (trace.duration * time_scale)
        # closed clauses ride along at their achieved rate
        closed_n = sum(c.requests for c in trace.closed)
        if closed_n:
            offered += closed_n / wall_s
    else:
        offered = completed / wall_s
    return LoadReport(
        trace=trace.describe(),
        arrivals=len(outcomes),
        completed=completed,
        failed=failed,
        wall_s=round(wall_s, 4),
        offered_rps=round(offered, 3),
        achieved_rps=round(completed / wall_s, 3),
        latency_ms={
            "p50": round(_percentile(lats, 0.50), 3),
            "p95": round(_percentile(lats, 0.95), 3),
            "p99": round(_percentile(lats, 0.99), 3),
            "max": round(lats[-1], 3) if lats else 0.0,
            "mean": round(sum(lats) / len(lats), 3) if lats else 0.0,
        },
        slo_ms=None if slo_ms is None else float(slo_ms),
        slo_misses=misses,
        slo_miss_rate=round(misses / completed, 6) if completed else 0.0,
        tenants=per_tenant,
        fairness=round(fairness_index(shares), 6),
        queue_depth=list(queue_depth),
        device_occupancy=list(device_occupancy),
        dispatches=dispatches,
        requeued=requeued,
        inflight_depth=dict(inflight_depth or {}),
        onboard=onb,
        remeshes=dict(rmsh),
    )


class _Sampler:
    """Bounded metrics-registry sampler: queue depth + mean device
    occupancy at ``period_s`` cadence, decimating 2:1 past the cap."""

    def __init__(self, t0: float, period_s: float):
        self.t0 = t0
        self.period = max(float(period_s), 1e-4)
        self.last = -float("inf")
        self.queue: list = []
        self.occ: list = []
        self._gauge = _metrics.gauge("batch.queue_depth")

    def sample(self) -> None:
        now = time.monotonic()
        if now - self.last < self.period:
            return
        self.last = now
        t_rel = round(now - self.t0, 4)
        self.queue.append((t_rel, self._gauge.value))
        occ = _metrics.label_values("fleet.device_occupancy", "device")
        vals = [
            v for v in occ.values()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        ]
        if vals:
            self.occ.append((t_rel, round(sum(vals) / len(vals), 4)))
        if len(self.queue) > _SAMPLE_CAP:
            self.queue = self.queue[::2]
            self.occ = self.occ[::2]
            self.period *= 2.0


def _default_ingest_source(index: int, size: int):
    """Distinct unseen-structure COO per ingest arrival: an ``n×n``
    diagonally-dominant profile with ``3n`` random off-diagonals, seeded
    by the arrival index so a seeded trace replays the same sequence of
    (whp unique) sparsity structures."""
    import numpy as np

    n = max(int(size), 2)
    rng = np.random.default_rng(0x1A9E57 + 7919 * index)
    k = min(3 * n, n * n - n)
    r = rng.integers(0, n, size=k)
    c = rng.integers(0, n, size=k)
    d = np.arange(n)
    rows = np.concatenate([d, r])
    cols = np.concatenate([d, c])
    vals = np.concatenate(
        [np.full(n, float(n)), 0.1 * rng.standard_normal(k)]
    )
    return rows, cols, vals, (n, n)


def run_load(session, trace: ArrivalTrace, systems, *, pattern=None,
             tol: float = 1e-8, maxiter=None, time_scale: float = 1.0,
             coalesce_s: float = 0.01, sample_period_s: float = 0.02,
             record: bool = True,
             pipeline: bool | None = None,
             ingest_source=None) -> LoadReport:
    """Drive ``session`` through ``trace`` and return the
    :class:`LoadReport`.

    ``systems`` is a sequence of ``(A, b)`` pairs cycled over arrivals
    (with ``pattern=`` given, ``(values, b)`` pairs over that shared
    pattern — skips per-request fingerprinting). Timed arrivals pace by
    wall clock (virtual seconds × ``time_scale``); while waiting for a
    far-off arrival the runner flushes queued work once the remaining
    wait exceeds ``coalesce_s`` (the microbatching window), and always
    flushes when the queue reaches ``session.batch_max``. Closed-loop
    clauses run after the timed phase: ``concurrency`` tickets are kept
    outstanding through the future API (await the oldest, top the
    window back up) until their request budget completes.

    ``pipeline`` selects the streaming-dispatch driving mode
    (ISSUE 13): ``True`` flushes without waiting
    (``session.flush(wait=False)``) and retires finished buckets
    opportunistically (``session.poll()``) while pacing, so the device
    solves bucket N while the runner packs bucket N+1; ``False`` is the
    classic blocking flush. Default ``None`` auto-selects from the
    session's window (pipelined iff ``session.inflight > 1``). Either
    way the run ends fully drained — every ticket is terminal in the
    report.

    Every request goes through the real ticket path — per-ticket
    latency is ``t_done - t_submit`` exactly as the ``batch.ticket``
    terminal events record it, and the tenant label rides the ticket
    (``SolveSession.submit(tenant=...)``).

    Arrivals with ``kind == "ingest"`` (the trace grammar's ``ingest``
    clause, ISSUE 18) route through ``session.ingest`` instead of the
    solve path: each submits a distinct unseen-structure COO
    (``ingest_source(index, size)`` — default a seeded random profile
    sized by the clause's ``size=``) and the report rolls onboarding
    latency (submit → ticket ready, background work included) into
    ``report.onboard`` — SEPARATE from the solve ``latency_ms``, so the
    serving p95 is measured while onboarding runs, never diluted by it.
    """
    systems = list(systems)
    if not systems:
        raise ValueError("run_load needs at least one (A, b) system")
    scale = float(time_scale)
    if not (scale > 0):
        raise ValueError(f"time_scale={time_scale} must be > 0")
    pipelined = (
        getattr(session, "inflight", 1) > 1 if pipeline is None
        else bool(pipeline)
    )
    ingest_src = ingest_source or _default_ingest_source
    ingest_tickets: list = []  # IngestTickets in submit order
    ingest_rejected = 0
    remesh_outcomes: list = []  # remesh-arrival outcomes in order
    t0 = time.monotonic()
    sampler = _Sampler(t0, sample_period_s)
    entries: list = []  # (tenant, ticket)
    idx = 0
    dispatch0 = session.dispatches
    depth_max = 0
    depth_sum = 0
    depth_n = 0

    def submit(tenant: str) -> None:
        nonlocal idx
        A, b = systems[idx % len(systems)]
        idx += 1
        kw = {"tol": tol, "maxiter": maxiter,
              "tenant": tenant if tenant else None}
        if pattern is not None:
            kw["pattern"] = pattern
        entries.append((tenant, session.submit(A, b, **kw)))

    def note_depth(outstanding: int) -> None:
        nonlocal depth_max, depth_sum, depth_n
        depth_max = max(depth_max, outstanding)
        depth_sum += outstanding
        depth_n += 1

    first_unresolved = 0

    def note_timed_depth() -> None:
        # tickets submitted-but-unresolved right now; retirement is
        # FIFO, so advancing a pointer over the resolved prefix is
        # O(1) amortized and exact up to out-of-order requeues
        nonlocal first_unresolved
        while (
            first_unresolved < len(entries)
            and entries[first_unresolved][1].t_done is not None
        ):
            first_unresolved += 1
        note_depth(len(entries) - first_unresolved)

    # -- timed phase -------------------------------------------------------
    coalesce = max(float(coalesce_s), 1e-4)
    for a in trace.arrivals:
        target = t0 + a.t * scale
        while True:
            now = time.monotonic()
            if now >= target:
                break
            if session.pending and target - now > coalesce:
                session.flush(wait=not pipelined)
                sampler.sample()
                continue
            if pipelined:
                session.poll()  # retire whatever already finished
            sampler.sample()
            time.sleep(min(target - now, coalesce))
        kind = getattr(a, "kind", "solve")
        if kind == "ingest":
            # background onboarding plane: never a solve ticket, never
            # a flush — the Onboarder's worker thread does the rest
            try:
                ingest_tickets.append(session.ingest(
                    ingest_src(len(ingest_tickets) + ingest_rejected,
                               a.size)
                ))
            except Exception:  # noqa: BLE001 - admission-reject counted
                ingest_rejected += 1
        elif kind == "remesh":
            # scheduled topology change (ISSUE 20): route through the
            # session's elastic path; to=N forges the target mesh,
            # to=0 re-resolves the live default. In-flight lanes
            # migrate with their best iterate — the trace's solve
            # tickets must still all reach terminal states.
            try:
                mesh = None
                if a.size > 0:
                    from ..fleet import fleet_mesh
                    mesh = fleet_mesh(a.size)
                res = session.remesh(mesh)
                remesh_outcomes.append(
                    str((res or {}).get("outcome", "?")))
            except Exception:  # noqa: BLE001 - rolled up as 'error'
                remesh_outcomes.append("error")
        else:
            submit(a.tenant)
        sampler.sample()
        if session.pending >= session.batch_max:
            session.flush(wait=not pipelined)
            note_timed_depth()
            sampler.sample()
    if session.pending:
        session.flush(wait=not pipelined)
        note_timed_depth()
        sampler.sample()

    # -- closed-loop phase: drive the ticket future API --------------------
    for c in trace.closed:
        submitted = 0
        outstanding: list = []  # tickets awaiting resolution, FIFO
        while submitted < c.requests or outstanding:
            while (
                submitted < c.requests
                and len(outstanding) < max(int(c.concurrency), 1)
            ):
                submit(c.tenant)
                outstanding.append(entries[-1][1])
                submitted += 1
            session.flush(wait=not pipelined)
            sampler.sample()
            # achieved (not just requested) concurrency: unresolved
            # tickets at the moment of the await
            note_depth(sum(1 for tk in outstanding if tk.t_done is None))
            tk = outstanding.pop(0)
            try:
                tk.result()
            except Exception:  # noqa: BLE001 - failures counted below
                pass
            sampler.sample()

    # fully drain the pipeline: the report accounts every ticket
    if hasattr(session, "drain"):
        session.drain()
    else:  # pragma: no cover - pre-pipeline session duck-compat
        session.flush()
    sampler.sample()

    wall_s = time.monotonic() - t0
    # onboarding completes AFTER the solve wall is closed: waiting on
    # background tickets here cannot inflate achieved_rps or the solve
    # percentiles (each ticket's wall_ms was stamped when it finished)
    if ingest_tickets:
        deadline = time.monotonic() + 120.0
        for tk in ingest_tickets:
            tk.wait(timeout=max(deadline - time.monotonic(), 0.0))
    now = time.monotonic()
    outcomes = []
    for tenant, tk in entries:
        end = tk.t_done if tk.t_done is not None else now
        outcomes.append(
            (tenant, max(end - tk.t_submit, 0.0), tk.done, tk.requeued)
        )
    inflight_depth = {}
    if depth_n:
        inflight_depth = {
            "max": depth_max,
            "mean": round(depth_sum / depth_n, 3),
            "pipelined": pipelined,
        }
    rep = build_report(
        trace, outcomes, wall_s, slo_ms=session.slo_ms,
        time_scale=scale, queue_depth=sampler.queue,
        device_occupancy=sampler.occ,
        dispatches=session.dispatches - dispatch0,
        inflight_depth=inflight_depth,
        onboard=[
            (tk.wall_ms, tk.state == "ready", bool(tk.dedup))
            for tk in ingest_tickets
        ],
        onboard_rejected=ingest_rejected,
        remeshes=remesh_outcomes,
    )
    if record:
        _recorder.record(
            "loadgen.trace", trace=rep.trace, arrivals=rep.arrivals,
            completed=rep.completed, failed=rep.failed,
            wall_s=rep.wall_s, offered_rps=rep.offered_rps,
            achieved_rps=rep.achieved_rps,
            p50_ms=rep.latency_ms["p50"], p95_ms=rep.latency_ms["p95"],
            p99_ms=rep.latency_ms["p99"], slo_ms=rep.slo_ms,
            slo_miss_rate=rep.slo_miss_rate, fairness=rep.fairness,
            tenants={
                k: {"completed": v["completed"],
                    "achieved_rps": v["achieved_rps"],
                    "weight": v["weight"]}
                for k, v in rep.tenants.items()
            },
            dispatches=rep.dispatches,
            **({"inflight_depth": rep.inflight_depth}
               if rep.inflight_depth else {}),
            **({"onboard": rep.onboard} if rep.onboard else {}),
            **({"remeshes": rep.remeshes} if rep.remeshes else {}),
        )
    return rep
