"""Fleet: the mesh-sharded serving tier behind ``SolveSession``.

The batch subsystem (:mod:`sparse_tpu.batch`) coalesces same-pattern
traffic into bucketed masked-Krylov dispatches — but every dispatch runs
on ONE device. The distributed layer (:mod:`sparse_tpu.parallel.dist`)
spans the mesh — but solves one system at a time. Fleet fuses them so a
single session serves a whole pod (ROADMAP item 1; the reference treats
distribution as first-class, SURVEY §2c/§3.2–3.3):

* **batch-sharded** (:func:`build_batch_program`) — the same-pattern
  serving shape. The SELL pattern plan is a replicated closure constant;
  the ``(B, nnz)`` value stack, the rhs, x0 and the per-lane tolerances
  shard across the mesh batch axis under ``shard_map``. Each device runs
  the ordinary masked-Krylov loop over its local lanes; the
  all-converged exit is GLOBAL — a per-iteration lane-count ``psum``
  through the :mod:`sparse_tpu.parallel.comm` wrappers (so the
  ``comm.collectives`` / ``comm.collective_bytes`` metrics and the
  ``comm.measured`` reconciliation come for free) keeps every shard on
  the same iteration until the last lane anywhere freezes. Per-lane
  iterates are bit-identical to the single-device program: lanes never
  exchange data, only the exit predicate crosses the mesh.
* **row-sharded** (:func:`build_row_program`) — single systems too large
  for one device. The submission becomes a B=1 bucket program wrapping
  ``shard_csr``/``dist_cg`` (row-block layout, halo-exchange SpMV, GSPMD
  psum reductions), so oversized traffic flows through the SAME
  ticket/flush/requeue path as everything else instead of bypassing the
  session.

Strategy selection is per (pattern, bucket): :class:`FleetPolicy.decide`
picks batch-sharding when a bucket carries at least
``settings.fleet_min_b`` real lanes, row-sharding for lone oversized CG
systems, and the unchanged single-device path otherwise (a 1-device mesh
ALWAYS selects single — the compiled program is byte-identical to
non-fleet mode, pinned by jaxpr-identity tests).

Compiled programs live in the ordinary plan cache under keys that embed
the :func:`~sparse_tpu.parallel.mesh.mesh_fingerprint`, and the vault
warm-start manifest records the fingerprint per program — a restart on a
different topology cold-starts cleanly instead of mis-replaying programs
compiled for the old mesh.

Enable with ``SPARSE_TPU_FLEET=auto`` (or ``batch`` / ``row`` to
restrict; docs/batching.md "Serving across a mesh").
"""

from __future__ import annotations

import numpy as np

from ..config import settings
from ._shard import (  # noqa: F401
    FLEET_AXIS,
    batch_comm_model_bytes,
    batch_ledger,
    build_batch_program,
    shard_inputs,
)
from ._row import build_row_program  # noqa: F401

__all__ = [
    "FLEET_AXIS", "FleetPlan", "FleetPolicy", "batch_comm_model_bytes",
    "batch_ledger", "build_batch_program", "build_row_program",
    "device_lane_counts", "fleet_mesh", "shard_inputs",
]

#: default row-sharding threshold: a single system at or beyond this many
#: rows routes through DistCSR/dist_cg instead of a one-lane batch
#: program (overridable per session via ``row_shard_min_n``)
ROW_SHARD_MIN_N = 1 << 18

_MODES = ("auto", "batch", "row")


def fleet_mesh(num_shards: int | None = None):
    """The fleet's 1-D serving mesh over the visible devices, batch axis
    named :data:`FLEET_AXIS` (row-sharded programs reuse the same mesh —
    their row-block axis is the same physical ring)."""
    from ..parallel.mesh import get_mesh

    return get_mesh(num_shards, axis=FLEET_AXIS)


def device_lane_counts(nb: int, bucket: int, S: int) -> list:
    """Real lanes per device for a block-sharded bucket: lanes are a
    real-first prefix of the padded stack and shard_map splits the batch
    axis into S contiguous blocks, so device ``d`` owns lanes
    ``[d*bucket/S, (d+1)*bucket/S)`` and its real count is the overlap
    with ``[0, nb)``. The per-device occupancy surface of
    ``session_stats()`` and the ``fleet.shard`` events."""
    per = max(int(bucket) // max(int(S), 1), 1)
    return [
        max(0, min(int(nb) - d * per, per)) for d in range(max(int(S), 1))
    ]


class FleetPlan:
    """One strategy decision: how a particular (pattern, bucket)
    dispatches. ``key_suffix`` is what the decision contributes to the
    bucket program's plan-cache key — empty for the single-device path
    (so fleet-off and mesh=1 share keys, programs and vault manifests
    with the classic session)."""

    __slots__ = ("strategy", "mesh", "fingerprint")

    def __init__(self, strategy: str, mesh=None, fingerprint: str | None = None):
        self.strategy = strategy
        self.mesh = mesh
        self.fingerprint = fingerprint

    @property
    def sharded(self) -> bool:
        return self.strategy != "single"

    @property
    def S(self) -> int:
        return 1 if self.mesh is None else int(self.mesh.devices.size)

    @property
    def key_suffix(self) -> str:
        if not self.sharded:
            return ""
        return f".{self.strategy}[{self.fingerprint}]"

    def __repr__(self):
        return (
            f"FleetPlan({self.strategy!r}, S={self.S}, "
            f"mesh={self.fingerprint!r})"
        )


_SINGLE = FleetPlan("single")


class FleetPolicy:
    """Per-session strategy selector (constructed by ``SolveSession``).

    Parameters
    ----------
    mode : '' (disabled) | 'auto' | 'batch' | 'row'
    mesh : the serving mesh (default: :func:`fleet_mesh` over every
        visible device). A 1-device mesh disables sharding outright.
    min_b : minimum REAL lanes before a bucket batch-shards
        (default ``settings.fleet_min_b``)
    row_min_n : row threshold for the oversized-single-system strategy
        (default :data:`ROW_SHARD_MIN_N`)
    """

    def __init__(self, mode: str = "", mesh=None, min_b: int | None = None,
                 row_min_n: int | None = None):
        mode = _canonical_mode(mode)
        self.mode = mode
        self.min_b = int(min_b if min_b is not None else settings.fleet_min_b)
        self.row_min_n = int(
            row_min_n if row_min_n is not None else ROW_SHARD_MIN_N
        )
        self.mesh = None
        self.fingerprint = None
        # elastic-mesh state (fleet/elastic.py): the flap guard's
        # single-strategy pin — None normally, a reason string once
        # latched (decide() then always answers single, like mesh=1)
        self.pinned = None
        if mode:
            from ..parallel.mesh import mesh_fingerprint

            self.mesh = mesh if mesh is not None else fleet_mesh()
            self.fingerprint = mesh_fingerprint(self.mesh)

    @classmethod
    def resolve(cls, fleet=None, mesh=None, min_b=None, row_min_n=None):
        """The ``SolveSession`` constructor hook: ``fleet`` may be a
        ready policy, a mode string, ``True`` (= 'auto'), ``False``
        (= off regardless of env), or ``None`` (= ``settings.fleet``)."""
        if isinstance(fleet, cls):
            return fleet
        if fleet is None:
            mode = settings.fleet
        elif fleet is False:
            mode = ""
        elif fleet is True:
            mode = "auto"
        else:
            mode = str(fleet)
        return cls(mode, mesh=mesh, min_b=min_b, row_min_n=row_min_n)

    @property
    def enabled(self) -> bool:
        return bool(self.mode) and self.S > 1 and self.pinned is None

    def retarget(self, mesh) -> None:
        """Re-point the policy at a new serving mesh (the elastic-mesh
        re-plan hook, fleet/elastic.py): later ``decide()`` calls plan
        against the new fingerprint, so program keys, bucket multiples
        and manifest notes all follow the topology. No-op semantics for
        the caller to enforce (the session's ``_do_remesh`` compares
        identities first). A mesh collapsed to 1 device degrades to the
        single-device path through the ordinary ``enabled`` check —
        nothing special-cased here."""
        if not self.mode:
            return  # fleet off: there is no mesh to re-point
        from ..parallel.mesh import mesh_fingerprint

        self.mesh = mesh if mesh is not None else fleet_mesh()
        self.fingerprint = mesh_fingerprint(self.mesh)

    def pin_single(self, reason: str) -> None:
        """Latch the policy to the single-device strategy (the flap
        guard's terminal state, failover-registry style): ``enabled``
        goes False, every later ``decide()`` answers single, and
        ``describe()`` carries the reason so ``/session`` dashboards
        show WHY the mesh went dark. Sticky for the session's life."""
        self.pinned = str(reason)

    @property
    def S(self) -> int:
        return 1 if self.mesh is None else int(self.mesh.devices.size)

    def bucket_multiple(self) -> int:
        """What bucket sizes must be divisible by so batch-sharding stays
        available: the mesh size when the policy can batch-shard, else 1
        (bucketing must not inflate pads for strategies that cannot use
        the mesh)."""
        return self.S if self.enabled and self.mode in ("auto", "batch") else 1

    def decide(self, pattern, nb: int, solver: str) -> FleetPlan:
        """Strategy for a bucket of ``nb`` real lanes over ``pattern``
        (the bucket itself is derived FROM the decision — batch-sharded
        buckets round up to a mesh multiple, row-sharded buckets are
        exactly 1); single unless a sharded strategy clearly pays."""
        if not self.enabled:
            return _SINGLE
        if (
            self.mode in ("auto", "row")
            and nb == 1
            and solver == "cg"
            and int(pattern.shape[0]) >= self.row_min_n
        ):
            return FleetPlan("row", self.mesh, self.fingerprint)
        if self.mode in ("auto", "batch") and nb >= self.min_b:
            return FleetPlan("batch", self.mesh, self.fingerprint)
        return _SINGLE

    def plan_for(self, strategy: str) -> FleetPlan:
        """The plan a recorded manifest entry replays under (the entry
        already named its strategy; the fingerprint match happened
        upstream)."""
        if strategy == "single" or not self.enabled:
            return _SINGLE
        return FleetPlan(strategy, self.mesh, self.fingerprint)

    def describe(self) -> dict:
        """JSON-friendly mesh block for ``session_stats()``."""
        if not self.mode:
            return {"enabled": False, "devices": 1}
        return {
            "enabled": self.enabled,
            "mode": self.mode,
            "devices": self.S,
            "axis": None if self.mesh is None else self.mesh.axis_names[0],
            "fingerprint": self.fingerprint,
            "min_b": self.min_b,
            "row_min_n": self.row_min_n,
            # elastic-mesh state (fleet/elastic.py): present only once
            # the flap guard latched, so pre-elastic consumers of this
            # dict see no new key on healthy sessions
            **({"pinned": self.pinned} if self.pinned is not None else {}),
        }


def _canonical_mode(mode) -> str:
    """'' stays off; truthy spellings mean 'auto'; unknown modes raise
    (a typo'd SPARSE_TPU_FLEET must not silently serve single-device)."""
    mode = str(mode or "").strip().lower()
    if mode in ("", "0", "off", "false", "no"):
        return ""
    if mode in ("1", "on", "true", "yes"):
        return "auto"
    if mode not in _MODES:
        raise ValueError(
            f"fleet mode {mode!r} not one of {('',) + _MODES}"
        )
    return mode
