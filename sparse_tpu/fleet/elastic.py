"""Elastic mesh: live topology-change survival for the fleet tier.

The fleet serving tier (ISSUE 10) keys every compiled program, ledger
and occupancy gauge to ONE immutable mesh fingerprint — before this
module, a mesh resize or slice loss was only survivable *across
restarts* (the vault manifest's ``mesh_skipped`` replay path,
``batch/service.py::_manifest_plan``). This module makes the same seam
work LIVE (ISSUE 20, docs/resilience.md "Elastic topology"): a
:class:`MeshMonitor` detects that the world's topology no longer
matches the mesh a session serves on, the session quiesces and
migrates in-flight work (``SolveSession._do_remesh``), the
:class:`~sparse_tpu.fleet.FleetPolicy` re-targets
(:meth:`~sparse_tpu.fleet.FleetPolicy.retarget`), and the mesh-keyed
manifest turns the re-plan into a warm replay whenever the new
topology was ever seen before.

Detection is deliberately conservative — two triggers only:

* a **forged topology** from the ``mesh`` fault-grammar site
  (``shrink:mesh:to=4`` / ``swap:mesh`` / ``flap:mesh``,
  :func:`resilience.faults.mesh_view`), which makes the whole path
  drillable on the forced CPU mesh in CI; and
* the **explicit** ``session.remesh(mesh)`` verb, the production entry
  point for a controller that knows the topology changed.

With no mesh fault active, :meth:`MeshMonitor.resolve` returns the
construction-time mesh — ``changed()`` is False on every clean
dispatch, so the monitor adds nothing to the default path (the
default-off invariance contract, pinned by ``tests/test_elastic.py``).

The **flap guard**: every executed remesh counts against a bounded
budget (``SPARSE_TPU_REMESH_RETRIES``); once exhausted the monitor
latches (``fleet.remesh_latched`` gauge), the policy pins to the
single-device strategy (:meth:`FleetPolicy.pin_single`,
failover-registry style) and no further migration is attempted — a
topology that will not hold still serves degraded rather than
thrashing recompiles.
"""

from __future__ import annotations

import numpy as np

from ..resilience import faults as _faults
from ..telemetry import _metrics

__all__ = ["MeshMonitor", "mesh_identity"]

_REMESH_LATCHED = _metrics.gauge(
    "fleet.remesh_latched",
    help="1 once a session's flap guard latched (remesh budget "
    "exhausted; the session is pinned to the single-device strategy)",
)


def mesh_identity(mesh) -> tuple:
    """Full live identity of a mesh: ``(fingerprint, device-id
    tuple)``. The fingerprint alone cannot see a *swap* (same platform,
    same count, different physical devices); the device key alone is
    volatile across processes. Change detection compares both."""
    from ..parallel.mesh import mesh_device_key, mesh_fingerprint

    return (mesh_fingerprint(mesh), mesh_device_key(mesh))


class MeshMonitor:
    """Per-session topology watcher (constructed by ``SolveSession``
    for fleet sessions unless ``SPARSE_TPU_REMESH=0``).

    Holds the construction-time mesh (``mesh0``) as the ground truth of
    what the world looked like, resolves what the (possibly forged)
    world looks like NOW, and carries the flap-guard budget. It never
    mutates the session or the policy — the session's ``_do_remesh``
    drives every transition so ordering (quiesce -> requeue -> retarget
    -> replay) lives in one place."""

    def __init__(self, mesh0, retries: int | None = None):
        from ..config import settings

        self.mesh0 = mesh0
        self.identity0 = mesh_identity(mesh0)
        self.retries = int(
            settings.remesh_retries if retries is None else retries
        )
        self.remeshes = 0
        self.latched = False

    # -- forged-world resolution -----------------------------------------
    def _submesh(self, k: int):
        """``mesh0`` shrunk to its first ``k`` devices (the forged
        shrink: the devices that 'survived' are a prefix, matching how
        ``get_mesh`` would rebuild over the remaining world)."""
        from jax.sharding import Mesh

        devs = list(self.mesh0.devices.flat)
        k = max(min(int(k), len(devs)), 1)
        return Mesh(np.array(devs[:k]), self.mesh0.axis_names)

    def _swapped(self):
        """Same-count mesh over ``mesh0``'s devices in reverse order —
        the forged slice replacement: fingerprint identical, device
        identity different."""
        from jax.sharding import Mesh

        devs = list(self.mesh0.devices.flat)
        return Mesh(np.array(devs[::-1]), self.mesh0.axis_names)

    def resolve(self):
        """The mesh the world currently offers: the forged topology
        when a ``mesh`` fault clause is live, else ``mesh0``. Pure and
        idempotent — consuming a disruption fire is the caller's
        explicit step (:func:`resilience.faults.mesh_disrupt`)."""
        if _faults.ACTIVE:
            view = _faults.mesh_view()
            if view is not None:
                kind, to = view
                if kind == "shrink":
                    s0 = len(list(self.mesh0.devices.flat))
                    return self._submesh(
                        to if to is not None else max(s0 // 2, 1)
                    )
                if kind == "swap":
                    return self._swapped()
        return self.mesh0

    def changed(self, policy):
        """The target mesh when the world differs from what ``policy``
        currently serves on, else ``None``. With no mesh fault active
        ``resolve()`` is ``mesh0`` — a policy still on its construction
        mesh always answers ``None`` here, so clean traffic never pays
        more than this comparison (and only ever reaches it from the
        fault gate / dispatch-error handler, never the hot path)."""
        if policy.mesh is None:
            return None
        target = self.resolve()
        if mesh_identity(target) != mesh_identity(policy.mesh):
            return target
        return None

    # -- flap guard -------------------------------------------------------
    def guard(self) -> bool:
        """Count one executed remesh against the flap budget. Returns
        True once the budget is exhausted — the caller must then latch
        (pin the policy single, stop migrating). ``retries`` remeshes
        are allowed; the next one latches."""
        self.remeshes += 1
        if self.remeshes > self.retries:
            self.latched = True
            _REMESH_LATCHED.set(1)
        return self.latched

    def describe(self) -> dict:
        """JSON-friendly elastic block for ``session_stats()`` /
        ``/healthz``."""
        return {
            "remeshes": self.remeshes,
            "retries": self.retries,
            "latched": self.latched,
        }
