"""Batch-sharded bucket programs: one masked-Krylov loop over the pod.

The serving-shape observation that makes this the cheap strategy: lanes
of a same-pattern bucket are *independent* systems, so sharding the
batch axis moves ZERO solver data over the interconnect — the SELL
pattern plan is a replicated closure constant, every matvec/inner
product is lane-local, and the only collective in the whole program is
the all-converged exit (one lane-count ``psum`` per iteration) that
keeps all shards on the same global step. Per-lane iterates are
therefore bit-identical to the single-device program, which is the
parity contract ``tests/test_fleet.py`` pins at machine eps.

The psum routes through :mod:`sparse_tpu.parallel.comm`, so its
trace-time payload lands on a per-(mesh, solver, bucket, dtype)
:class:`~sparse_tpu.parallel.comm.SiteLedger` under the ``fleet.batch``
site; ``SolveSession`` commits the observed execution count after each
dispatch (always-on ``comm.collectives`` / ``comm.collective_bytes``)
and reconciles against :func:`batch_comm_model_bytes` in a
``comm.measured`` event. The model counts one psum per *iteration*, the
measurement one per while-condition evaluation (iterations + 1) — the
same small-positive expected divergence convention as ``dist.cg``.

GMRES keeps its host-driven restart loop (one host sync per cycle), so
its fleet form shards the *data* instead of the program: inputs are
``device_put`` onto the mesh batch axis and GSPMD partitions the
batched Arnoldi cycle (lanes independent ⇒ no resharding; the cycle's
``jnp.any(~done)`` becomes the inserted all-reduce). Its collective
traffic is GSPMD-inserted and thus model-only — the documented wrapper
blind spot (docs/telemetry.md).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..ops import spmv as spmv_ops
from ..parallel import comm
from ..parallel.mesh import shard_map

#: the fleet mesh's batch axis name (bucket lane stacks shard over it)
FLEET_AXIS = "lanes"


def batch_ledger(fingerprint: str, solver: str, bucket: int, dtype):
    """The shared :class:`~sparse_tpu.parallel.comm.SiteLedger` of one
    batch-sharded program geometry — keyed so a jit-cached program for
    one (mesh, solver, bucket, dtype) never commits against bytes a
    different geometry's trace noted."""
    return comm.ledger(
        "fleet.batch",
        key=(str(fingerprint), str(solver), int(bucket), np.dtype(dtype).str),
    )


def batch_comm_model_bytes(S: int, iters: int, itemsize: int = 4) -> int:
    """Analytic collective model of a batch-sharded solve: one lane-count
    psum (a single int32 per shard, logical-payload convention) per
    iteration, across ``S`` shards. The measured side additionally pays
    the final while-condition evaluation — divergence ``~ 1/iters``,
    inside the 10% gate for any real solve."""
    return int(itemsize) * int(iters) * int(S)


def shard_inputs(mesh, *arrays):
    """``device_put`` each array onto the mesh batch axis (leading dim).
    The GSPMD entry of the gmres strategy, also used by benches/tests to
    stage pre-sharded traffic."""
    sh = NamedSharding(mesh, P(mesh.axis_names[0]))
    return tuple(jax.device_put(jnp.asarray(a), sh) for a in arrays)


def build_batch_program(pattern, bkt: int, dt, solver: str, mesh,
                        conv_test_iters: int, gmres_inner=None,
                        m_factory=None, mixed=None):
    """The mesh-sharded analog of ``SolveSession._build_program``: one
    compiled program whose arguments are the bucket's ``(B, nnz)`` value
    stack, ``(B, n)`` rhs/x0, per-lane tolerances and maxiter, with the
    batch axis sharded over ``mesh``. ``bkt`` must be a multiple of the
    mesh size (``bucket.bucket_batch(..., multiple_of=S)``).

    cg/bicgstab run under ``shard_map`` with the global psum exit;
    gmres wraps ``gmres_inner`` (the session's host-driven closure) with
    input sharding and lets GSPMD partition the cycle.

    ``m_factory`` is the resolved preconditioner's numeric factory
    (ISSUE 14, :mod:`sparse_tpu.precond`): its pattern-level maps are
    closure constants — REPLICATED across the mesh exactly like the
    SELL pattern plan — and the numeric factorization runs inside the
    ``shard_map`` body over each device's LOCAL ``(B/S, nnz)`` value
    shard. Preconditioning is lane-local (diag/block extraction,
    fixed-sweep factorization sweeps, triangular sweeps are all
    per-lane), so it adds ZERO collectives to the sharded program and
    per-lane iterates stay bit-identical to the single-device
    preconditioned program.

    ``mixed`` is the resolved reduced-precision policy's knob dict
    (ISSUE 15, ``{'policy', 'inner_iters', 'max_outer', 'eta'}``):
    when given, the shard_map body runs the fused iterative-refinement
    loop instead — each device downcasts its LOCAL value shard, the
    all-converged psum exit threads through BOTH the f64 outer loop and
    the reduced inner sweeps (every shard runs the same global sweep
    schedule, frozen lanes bit-stable), and the program returns the
    refinement sweep count as a 5th (replicated) output.
    """
    from ..batch import krylov

    S = int(mesh.devices.size)
    if int(bkt) % S:
        raise ValueError(f"bucket {bkt} not a multiple of mesh size {S}")
    axis = mesh.axis_names[0]

    if solver == "gmres":
        if gmres_inner is None:
            raise ValueError("gmres strategy needs the inner closure")

        def run_gmres(values, rhs, x0, tols, maxiter):
            values, rhs, x0, tols = shard_inputs(mesh, values, rhs, x0, tols)
            return gmres_inner(values, rhs, x0, tols, maxiter)

        return run_gmres

    from ..parallel.mesh import mesh_fingerprint

    pack = pattern.sell_pack()
    idx_slabs, pos, zero_rows = (
        pack.idx_slabs, pack.pos, pack.plan.zero_rows
    )
    loop = krylov._cg_loop if solver == "cg" else krylov._bicgstab_loop
    cti = int(conv_test_iters)
    led = batch_ledger(mesh_fingerprint(mesh), solver, bkt, dt)

    def lane_reduce(active):
        # the GLOBAL all-converged exit: per-iteration lane-count psum
        # through the accounting wrapper (4 bytes/shard/evaluation on
        # the ledger; SolveSession commits the observed executions)
        # dtype pinned: jnp.sum would promote to int64 under x64 and
        # silently double the psum payload vs batch_comm_model_bytes
        n_active = comm.psum(
            jnp.sum(active, dtype=jnp.int32), axis,
            ledger=led, tag="all_converged",
        )
        return n_active > 0

    if mixed is not None:
        from .. import mixed as mixed_mod

        storage_dt, compute_dt = mixed_mod.inner_dtypes(mixed["policy"])
        sdt = jnp.dtype(storage_dt)
        cdt = jnp.dtype(compute_dt)
        wdt = jnp.dtype(mixed_mod.outer_dtype())
        inner_iters = int(mixed["inner_iters"])
        max_outer = int(mixed["max_outer"])
        eta = float(mixed["eta"])

        def body(values, rhs, x0, tols, maxiter):
            req_dt = values.dtype
            vals_w = pack.pack_values(values.astype(wdt))
            vals_l = pack.pack_values(values.astype(sdt))

            def mv_wide(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals_w, pos, X, zero_rows
                )

            def mv_low(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals_l, pos, X, zero_rows, acc_dtype=cdt
                )

            fmv_low = krylov._maybe_faulty_mv(mv_low)
            Mvec = (
                None if m_factory is None
                else m_factory(values.astype(cdt), fmv_low)
            )
            X, iters, resid2, conv, outer = mixed_mod.ir_loop(
                mv_wide, fmv_low, rhs, x0, tols, maxiter, cti,
                inner_iters, max_outer, eta, cdt, Mvec=Mvec,
                solver=solver, lane_reduce=lane_reduce,
            )
            return X.astype(req_dt), iters, resid2, conv, outer

        out_specs = (P(axis), P(axis), P(axis), P(axis), P())
    else:
        def body(values, rhs, x0, tols, maxiter):
            vals = pack.pack_values(values)

            def mv(X):
                return spmv_ops.csr_spmv_sell_batched(
                    idx_slabs, vals, pos, X, zero_rows
                )

            fmv = krylov._maybe_faulty_mv(mv)
            # lane-local numeric factorization from this shard's value
            # stack; the factory's maps ride in as replicated constants
            Mvec = None if m_factory is None else m_factory(values, fmv)
            return loop(
                fmv, rhs, x0, tols, maxiter, cti, Mvec=Mvec,
                lane_reduce=lane_reduce,
            )

        out_specs = (P(axis), P(axis), P(axis), P(axis))

    sharded = shard_map(
        body, mesh=mesh,
        in_specs=(P(axis), P(axis), P(axis), P(axis), P()),
        out_specs=out_specs,
        check_vma=False,
    )

    # sharded programs accept donated inputs too (ISSUE 13): the
    # value-stack/rhs/x0 shards are consumed once per dispatch, so on
    # TPU/GPU their HBM recycles exactly like the single-device
    # program's (no-op on CPU — see batch.service.donate_argnums)
    from ..batch.service import donate_argnums

    @partial(jax.jit, donate_argnums=donate_argnums())
    def run(values, rhs, x0, tols, maxiter):
        return sharded(values, rhs, x0, tols, jnp.asarray(maxiter))

    return run
