"""Row-sharded B=1 bucket programs: oversized systems through the mesh.

The complementary fleet strategy: a single system too large for one
device cannot batch-shard (there is nothing to split on the batch axis),
but it IS the shape :mod:`sparse_tpu.parallel.dist` was built for —
row-block layout over the mesh, halo-exchange SpMV, GSPMD psum
reductions inside one compiled CG while_loop. This module wraps that
path in a bucket-program signature (``run(values, rhs, x0, tols,
maxiter) -> (X, iters, resid2, converged)`` with a leading B=1 lane
axis), so oversized submissions flow through ``SolveSession``'s normal
ticket/flush/requeue machinery instead of bypassing the session: they
get deadlines, dispatch retries, terminal ``batch.ticket`` events and —
if the mesh solve comes back unconverged — the standard requeue into the
single-device fallback bucket.

Cost shape: the row-block *layout* is rebuilt per dispatch (values
change per request and ``DistCSR`` bakes them into its shard planes) and
``dist_cg`` retraces per call — acceptable because row-sharded traffic
is by definition rare and enormous (the solve dominates), and honest:
under streaming dispatch (ISSUE 13) the closure is host-driven, so a
row "dispatch" completes its solve before returning — the pipeline
treats it as ready-at-enqueue (its numpy outputs have no deferred
device exit to wait on) and the deferred-readback API still works
unchanged over it.
the program key still takes exactly one plan-cache miss per
(pattern, mesh), covering the *dispatcher* closure. Collective
accounting rides ``DistCSR``'s own ledger (``dist.cg`` site), so
``comm.measured`` reconciliation is inherited from Axon v4 unchanged.
"""

from __future__ import annotations

import numpy as np


class _HostCSR:
    """The duck ``shard_csr`` expects: host indptr/indices/data/shape."""

    __slots__ = ("indptr", "indices", "data", "shape")

    def __init__(self, indptr, indices, data, shape):
        self.indptr, self.indices = indptr, indices
        self.data, self.shape = data, shape


def _host_spmv(pattern, vals, x):
    """Host-side CSR matvec for the residual the ticket contract needs
    (complex-safe; empty rows contribute nothing)."""
    m = int(pattern.shape[0])
    seg = np.repeat(
        np.arange(m, dtype=np.int64), np.diff(pattern.indptr)
    )
    prod = vals * x[pattern.indices]
    if np.iscomplexobj(prod):
        return np.bincount(seg, weights=prod.real, minlength=m) + 1j * (
            np.bincount(seg, weights=prod.imag, minlength=m)
        )
    return np.bincount(seg, weights=prod, minlength=m)


def build_row_program(pattern, dt, mesh, conv_test_iters: int = 25,
                      make_M=None):
    """One row-sharded B=1 bucket program over ``pattern``.

    The returned ``run`` is a host closure (never jitted at this level —
    layout construction is host work); per call it lays the request's
    values out over the mesh (nnz-balanced row blocks), runs the
    compiled distributed CG to the lane's ABSOLUTE tolerance (the
    session contract: ``||r|| < tol``), and returns numpy lane stacks
    shaped exactly like a batch program's output.

    ``make_M`` (ISSUE 14 satellite) hooks a preconditioner into the
    distributed solve: a callable ``make_M(DistCSR) -> M`` invoked per
    dispatch after the row-block layout exists, returning anything
    ``dist_cg`` accepts as ``M`` — a padded-vector callable or a
    LinearOperator-shaped object (e.g. a multigrid V-cycle via
    :func:`sparse_tpu.parallel.multigrid.vcycle_operator`). Best-effort:
    a failing hook falls back to the unpreconditioned solve.
    """
    from ..parallel.dist import dist_cg, shard_csr

    axis = mesh.axis_names[0]
    dt = np.dtype(dt)
    cti = int(conv_test_iters)

    def run(values, rhs, x0, tols, maxiter):
        values = np.asarray(values).astype(dt, copy=False)
        rhs = np.asarray(rhs).astype(dt, copy=False)
        x0 = np.asarray(x0).astype(dt, copy=False)
        tols = np.asarray(tols, dtype=np.float64)
        if values.shape[0] != 1:
            raise ValueError(
                f"row-sharded programs serve B=1 buckets; got "
                f"B={values.shape[0]}"
            )
        A = _HostCSR(pattern.indptr, pattern.indices, values[0],
                     pattern.shape)
        D = shard_csr(A, mesh=mesh, axis=axis, balanced=True)
        M = None
        if make_M is not None:
            try:
                M = make_M(D)
            except Exception:  # noqa: BLE001 - best-effort hook
                M = None
        xp, iters, _conv = dist_cg(
            D, rhs[0], x0=(x0[0] if np.any(x0) else None),
            tol=0.0, atol=float(tols[0]), maxiter=int(maxiter),
            conv_test_iters=cti, M=M,
        )
        x = D.unpad_vector(xp).astype(dt, copy=False)
        r = rhs[0] - _host_spmv(pattern, values[0], x)
        resid2 = float(np.real(np.vdot(r, r)))
        conv = np.isfinite(resid2) and resid2 < float(tols[0]) ** 2
        return (
            x[None, :],
            np.asarray([int(iters)], dtype=np.int32),
            np.asarray([resid2], dtype=np.float64),
            np.asarray([conv], dtype=bool),
        )

    return run
