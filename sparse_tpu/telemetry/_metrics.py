"""Always-on metrics registry: counters, gauges, log-bucket histograms.

The event stream (:mod:`._recorder`) answers "what happened"; this module
answers "what is the level right now" — the surface a serving stack
scrapes. Before it existed the library kept three ad-hoc dicts
(``plan_cache._STATS``, the recorder's ``_COUNTS``/``_BYTES``, and
whatever ``SolveSession`` stashed per dispatch); they all live here now,
behind one registry with Prometheus text exposition
(:func:`metrics_text` / ``telemetry.metrics_text()``).

Design rules:

* **Always on.** Unlike the event stream, metrics are not gated by
  ``settings.telemetry`` — a counter bump is one dict hit plus one int
  add under a lock, cheap enough to leave on everywhere (the plan cache
  has counted always-on since PR 2). Call sites that *are*
  telemetry-gated (the recorder's ``count()``/``add_bytes``) keep their
  own gate; the registry itself never checks it.
* **Allocation-light.** Metric objects are created once
  (get-or-create keyed on ``(name, labels)``) and mutate plain
  ints/floats in place; histograms pre-allocate their bucket array.
  The hot path never builds strings or dicts.
* **Dotted names in, Prometheus names out.** Library code uses the
  repo's dotted convention (``plan_cache.hits``, ``batch.queue_depth``);
  :func:`metrics_text` sanitizes to ``sparse_tpu_plan_cache_hits_total``
  etc. at exposition time only.
"""

from __future__ import annotations

import math
import threading

_LOCK = threading.RLock()
# (name, ((label, value), ...)) -> metric object
_REGISTRY: dict = {}
# name -> metric class, for TYPE lines and family grouping
_FAMILIES: dict = {}
# name -> help text, for HELP lines (optional, set via help= at creation)
_HELP: dict = {}

# Log-2 histogram geometry: upper bounds 2**k for k in [_BK_MIN, _BK_MAX),
# plus a +Inf overflow bucket. Spans ~1e-6 .. ~1e9 — microseconds to
# gigabytes/iteration-counts on one fixed grid, so histograms never
# allocate per observation.
_BK_MIN = -20
_BK_MAX = 31
_BOUNDS = tuple(2.0 ** k for k in range(_BK_MIN, _BK_MAX))


def _labels_key(labels: dict) -> tuple:
    return tuple(sorted(labels.items()))


class Counter:
    """Monotonic counter. ``inc(n)`` under the registry lock."""

    __slots__ = ("name", "labels", "_v")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._v = 0

    def inc(self, n=1) -> None:
        with _LOCK:
            self._v += n

    add = inc  # byte-total call sites read better as .add(nbytes)

    @property
    def value(self):
        return self._v

    def reset(self) -> None:
        with _LOCK:
            self._v = 0


class Gauge:
    """Point-in-time level. ``fn`` makes a lazy gauge (sampled at read
    time — e.g. ``plan_cache.size`` reads ``len(_ENTRIES)`` live)."""

    __slots__ = ("name", "labels", "_v", "fn")

    def __init__(self, name: str, labels: dict, fn=None):
        self.name = name
        self.labels = labels
        self._v = 0.0
        self.fn = fn

    def set(self, v) -> None:
        with _LOCK:
            self._v = v

    def inc(self, n=1) -> None:
        with _LOCK:
            self._v += n

    def dec(self, n=1) -> None:
        with _LOCK:
            self._v -= n

    @property
    def value(self):
        if self.fn is not None:
            try:
                return self.fn()
            except Exception:
                return 0
        return self._v

    def reset(self) -> None:
        with _LOCK:
            self._v = 0.0


class Histogram:
    """Fixed log-2 bucket histogram (see ``_BOUNDS``): ``observe(v)``
    finds the bucket via ``math.frexp`` — no log calls, no allocation."""

    __slots__ = ("name", "labels", "_counts", "_sum", "_n")

    def __init__(self, name: str, labels: dict):
        self.name = name
        self.labels = labels
        self._counts = [0] * (len(_BOUNDS) + 1)  # +1: the +Inf bucket
        self._sum = 0.0
        self._n = 0

    def observe(self, v) -> None:
        v = float(v)
        if math.isnan(v):
            return
        if v <= 0.0:
            idx = 0
        elif math.isinf(v):
            idx = len(_BOUNDS)
        else:
            m, e = math.frexp(v)  # v = m * 2**e, m in [0.5, 1)
            k = e - 1 if m == 0.5 else e  # smallest k with v <= 2**k
            idx = min(max(k - _BK_MIN, 0), len(_BOUNDS))
        with _LOCK:
            self._counts[idx] += 1
            self._sum += v
            self._n += 1

    @property
    def count(self) -> int:
        return self._n

    @property
    def sum(self) -> float:
        return self._sum

    def buckets(self) -> list:
        """Cumulative ``(upper_bound, count)`` pairs, Prometheus-style
        (the last pair is ``(inf, total)``)."""
        with _LOCK:
            counts = list(self._counts)
        out = []
        acc = 0
        for b, c in zip(_BOUNDS, counts):
            acc += c
            out.append((b, acc))
        out.append((math.inf, acc + counts[-1]))
        return out

    def reset(self) -> None:
        with _LOCK:
            self._counts = [0] * (len(_BOUNDS) + 1)
            self._sum = 0.0
            self._n = 0


def _get(cls, name: str, labels: dict, help=None, **kw):
    key = (name, _labels_key(labels))
    with _LOCK:
        if help:
            _HELP.setdefault(name, str(help))
        m = _REGISTRY.get(key)
        if m is None:
            m = cls(name, dict(labels), **kw)
            _REGISTRY[key] = m
            _FAMILIES.setdefault(name, cls)
        return m


def counter(name: str, /, help=None, **labels) -> Counter:
    """Get-or-create a counter (same name+labels => same object).
    ``help`` registers the family's HELP text (first writer wins)."""
    return _get(Counter, name, labels, help=help)


def gauge(name: str, /, fn=None, help=None, **labels) -> Gauge:
    """Get-or-create a gauge; ``fn`` makes it lazily sampled."""
    g = _get(Gauge, name, labels, help=help)
    if fn is not None:
        g.fn = fn
    return g


def histogram(name: str, /, help=None, **labels) -> Histogram:
    """Get-or-create a log-2 bucket histogram."""
    return _get(Histogram, name, labels, help=help)


def family(name: str) -> list:
    """Every metric object registered under ``name`` (all label sets) —
    the readback windowed consumers (the SLO watchdog) aggregate over,
    e.g. total ticket count across per-solver/tenant latency histograms."""
    with _LOCK:
        return [m for (n, _), m in _REGISTRY.items() if n == name]


def label_values(name: str, label: str) -> dict:
    """``{label_value: metric_value}`` over a family — the readback the
    recorder's ``counters()``/``bytes_by_kind()`` use."""
    with _LOCK:
        items = [m for (n, _), m in _REGISTRY.items() if n == name]
    return {m.labels.get(label, ""): m.value for m in items}


def remove(name: str) -> None:
    """Drop a whole family from the registry (``telemetry.reset()`` uses
    this for the dynamic-name recorder families; metrics held as module
    globals should ``reset()`` their values instead)."""
    with _LOCK:
        for key in [k for k in _REGISTRY if k[0] == name]:
            del _REGISTRY[key]
        _FAMILIES.pop(name, None)
        _HELP.pop(name, None)


def zero(prefix: str = "") -> None:
    """Reset every matching metric's value in place (objects stay
    registered and call-site references stay live)."""
    with _LOCK:
        metrics = [m for (n, _), m in _REGISTRY.items() if n.startswith(prefix)]
    for m in metrics:
        m.reset()


def _sanitize(name: str) -> str:
    out = []
    for ch in name:
        out.append(ch if (ch.isalnum() and ch.isascii()) or ch in "_:" else "_")
    s = "".join(out)
    if s and s[0].isdigit():
        s = "_" + s
    return s


def _escape_label(v) -> str:
    """Prometheus label-value escaping (exposition format 0.0.4):
    backslash, double-quote and newline must be escaped — unescaped they
    corrupt the whole scrape, not just one series."""
    return (
        str(v)
        .replace("\\", "\\\\")
        .replace('"', '\\"')
        .replace("\n", "\\n")
    )


def _escape_help(s: str) -> str:
    """HELP-text escaping: backslash and newline only (quotes are legal
    in HELP lines per the format spec)."""
    return str(s).replace("\\", "\\\\").replace("\n", "\\n")


def _fmt_labels(labels: dict) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{_sanitize(str(k))}="{_escape_label(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    if math.isinf(f):
        return "+Inf" if f > 0 else "-Inf"
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def metrics_text() -> str:
    """Prometheus text exposition (format 0.0.4) of the whole registry.

    Dotted names become ``sparse_tpu_<name>`` with non-alphanumerics
    mapped to ``_``; counters gain the conventional ``_total`` suffix,
    histograms expose ``_bucket``/``_sum``/``_count`` series. Every
    family gets ``# HELP`` + ``# TYPE`` lines (registered help text, or
    the dotted name as the fallback description), and label values are
    escaped per the format spec (:func:`_escape_label`).
    """
    with _LOCK:
        families = dict(_FAMILIES)
        helps = dict(_HELP)
        by_name: dict = {}
        for (name, _), m in sorted(_REGISTRY.items()):
            by_name.setdefault(name, []).append(m)
    lines = []
    for name in sorted(by_name):
        cls = families.get(name, Counter)
        base = "sparse_tpu_" + _sanitize(name)
        help_text = _escape_help(helps.get(name, f"sparse_tpu {name}"))
        if cls is Counter:
            lines.append(f"# HELP {base}_total {help_text}")
            lines.append(f"# TYPE {base}_total counter")
            for m in by_name[name]:
                lines.append(
                    f"{base}_total{_fmt_labels(m.labels)} {_fmt_value(m.value)}"
                )
        elif cls is Gauge:
            lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} gauge")
            for m in by_name[name]:
                lines.append(
                    f"{base}{_fmt_labels(m.labels)} {_fmt_value(m.value)}"
                )
        else:  # Histogram
            lines.append(f"# HELP {base} {help_text}")
            lines.append(f"# TYPE {base} histogram")
            for m in by_name[name]:
                for bound, acc in m.buckets():
                    lb = dict(m.labels)
                    lb["le"] = _fmt_value(bound)
                    lines.append(f"{base}_bucket{_fmt_labels(lb)} {acc}")
                lines.append(
                    f"{base}_sum{_fmt_labels(m.labels)} {_fmt_value(m.sum)}"
                )
                lines.append(
                    f"{base}_count{_fmt_labels(m.labels)} {m.count}"
                )
    return "\n".join(lines) + ("\n" if lines else "")


def snapshot() -> dict:
    """JSON-friendly flat view: ``{name{labels}: value}`` for counters
    and gauges, ``{name{labels}: {"count", "sum"}}`` for histograms —
    what bench.py embeds in its session record."""
    with _LOCK:
        items = list(_REGISTRY.items())
    out = {}
    for (name, lkey), m in sorted(items):
        key = name + _fmt_labels(dict(lkey))
        if isinstance(m, Histogram):
            out[key] = {"count": m.count, "sum": round(m.sum, 9)}
        else:
            v = m.value
            out[key] = round(v, 9) if isinstance(v, float) else v
    return out
