"""sparse_tpu.telemetry — structured observability for the whole stack.

The reference stack (legate.sparse) leans on Legion's built-in profiling
and mapper introspection to see where time and communication go; the
JAX/XLA reproduction has no such substrate, so this package provides
one: every solver run, kernel-tile decision and collective is measurable
through a single event stream.

Surface
-------
* :func:`record` — ``record(kind, **fields)``: one structured event into
  a bounded in-memory ring + the JSONL session log
  (``results/axon/records.jsonl``, shared with bench.py's
  hardware-evidence records). Zero overhead when disabled.
* :func:`count` / :func:`add_bytes` — in-memory counters for hot paths
  (kernel dispatches, host syncs, per-SpMV comm volumes) where an event
  per call would flood the log.
* :func:`span` — scoped wall-clock + optional device-sync timer
  (``with span("cg.iter"): ...``). Trace-safe: a shared no-op inside
  ``jit``/``scan`` traces; ``block_until_ready`` only at span exit.
* :func:`summary` — counts, per-kind event totals, span p50/p95
  latencies, bytes moved per collective family.
* :func:`events` / :func:`reset` / :func:`configure` / :func:`flush` —
  ring snapshot, state reset, sink redirection, sink flush.
* ``schema`` (module) — the event-kind table + ``validate`` /
  ``validate_jsonl`` used by tests and documented in docs/telemetry.md.

Enabled by ``SPARSE_TPU_TELEMETRY=1`` (or ``settings.telemetry = True``);
sink override via ``SPARSE_TPU_TELEMETRY_PATH`` / :func:`configure`.
"""

from __future__ import annotations

from . import _schema as schema  # noqa: F401
from ._recorder import (  # noqa: F401
    add_bytes,
    add_span,
    bytes_by_kind,
    configure,
    count,
    counters,
    enabled,
    events,
    flush,
    record,
    reset,
    sink_path,
)
from ._spans import Span, device_sync, span  # noqa: F401
from ._summary import summary  # noqa: F401

__all__ = [
    "add_bytes",
    "add_span",
    "bytes_by_kind",
    "configure",
    "count",
    "counters",
    "device_sync",
    "enabled",
    "events",
    "flush",
    "record",
    "reset",
    "schema",
    "sink_path",
    "span",
    "Span",
    "summary",
]
