"""sparse_tpu.telemetry — structured observability for the whole stack.

The reference stack (legate.sparse) leans on Legion's built-in profiling
and mapper introspection to see where time and communication go; the
JAX/XLA reproduction has no such substrate, so this package provides
one: every solver run, kernel-tile decision and collective is measurable
through a single event stream.

Surface
-------
* :func:`record` — ``record(kind, **fields)``: one structured event into
  a bounded in-memory ring + the JSONL session log
  (``results/axon/records.jsonl``, shared with bench.py's
  hardware-evidence records). Zero overhead when disabled.
* :func:`count` / :func:`add_bytes` — hot-path counters (kernel
  dispatches, host syncs, per-SpMV comm volumes) where an event per
  call would flood the log; stored on the metrics registry.
* :func:`span` — scoped wall-clock + optional device-sync timer
  (``with span("cg.iter"): ...``). Trace-safe: a shared no-op inside
  ``jit``/``scan`` traces; ``block_until_ready`` only at span exit.
* :func:`summary` — counts, per-kind event totals, span p50/p95
  latencies, bytes moved per collective family, ring drop count.
* :mod:`metrics <._metrics>` — the ALWAYS-ON registry (counters,
  gauges, log-bucket histograms) behind the plan-cache stats, recorder
  counters and SolveSession levels; :func:`metrics_text` is its
  Prometheus text exposition.
* :func:`serve` — the live serving exporter (:mod:`._serve`): a
  daemon-threaded stdlib HTTP server (OFF until called) exposing
  ``/metrics`` (Prometheus text), ``/healthz`` (anomalies, failover
  latches, fault-injection state), ``/session`` (queue depth, ticket
  states, program attribution) and ``/alerts`` (the watchdog's rule
  states); ``scripts/axon_serve.py`` is the CLI.
* :func:`watchdog` / :func:`watchdog_state` / :func:`stop_watchdog` —
  the SLO watchdog (:mod:`._watchdog`): declarative rules (SLO-miss
  rate, anomaly rate, queue saturation, occupancy floor, vault
  quarantines, failover latches) with hysteresis + cooldown, evaluated
  on a monotonic tick or on demand, emitting ``watchdog.alert`` /
  ``watchdog.clear`` events and the always-on
  ``watchdog.alerts{rule,severity}`` counter.
  :mod:`sparse_tpu.loadgen` is the traffic source that exercises it.
* :func:`flight` / :func:`capture_now` / :func:`flight_state` — the
  incident flight recorder (:mod:`._flight`, ISSUE 12): watchdog alert
  transitions capture rate-limited, count-bounded postmortem bundles
  (ring tail + identity, metrics/plan-cache snapshots, watchdog/health/
  session state, cost table, env/config/mesh fingerprint, Perfetto
  slice) under ``results/axon/incidents/``; ``scripts/axon_doctor.py``
  diagnoses a bundle, the exporter serves ``/incidents`` and
  ``/debug/capture``. Off unless ``SPARSE_TPU_FLIGHT`` is set.
* :func:`profile_capture` — on-demand ``jax.profiler`` trace window
  (:mod:`._profiler`); the same module sinks the sampled timed-dispatch
  host/device split ``batch/service.py`` records under
  ``SPARSE_TPU_PROFILE_EVERY`` (the measured ``device_ms`` column in
  ``axon_report``'s roofline table).
* :mod:`history <._history>` / :func:`start_history` /
  :func:`history_window` — the continuous-telemetry history store
  (Axon v7): a daemon sampler scraping the always-on registry into
  bounded in-memory rings (raw + 10x/60x min/max/mean/last rollups)
  and atomic, byte-capped on-disk segments under
  ``results/axon/history/``; off (zero overhead) unless
  ``SPARSE_TPU_HISTORY`` is set. ``scripts/axon_dash.py`` renders the
  segments; the exporter serves a live ``/dash``.
* :mod:`budget <._budget>` — the SLO error-budget engine (Axon v7):
  per-(tenant) windowed burn rates over the ticket-latency/SLO-miss
  families, the multi-window burn-rate watchdog rules
  (``slo_fast_burn`` pages on 5m/1h, ``slo_slow_burn`` warns on
  6h/3d — replacing the v5 instantaneous ``slo_miss_rate`` in
  :func:`~._watchdog.default_rules`), the per-tenant ``usage.*``
  metering rollup and the exporter's ``/budget`` payload.
* :func:`ticket_scope` / :func:`new_ticket_id` /
  :func:`current_tickets` — request-scoped trace context
  (:mod:`._context`): events recorded inside a scope carry the
  originating ticket ids, which is how one serving request stays
  traceable across ``batch.dispatch`` → ``kernel.failover`` →
  ``batch.requeue`` → its ``batch.ticket`` terminal event.
* :mod:`cost <._cost>` — compile-time cost attribution: AOT
  compile capture (wall-clock, XLA ``cost_analysis`` flops/bytes,
  ``memory_analysis`` peak HBM) per plan-cached program, feeding
  ``plan_cache.compile`` events, per-program gauges and
  ``axon_report``'s achieved-vs-roofline table.
* :func:`export_trace` — Chrome-trace/Perfetto JSON of the session
  (lanes per subsystem, nested spans) — ``scripts/axon_trace.py`` is
  the CLI over a records.jsonl.
* :mod:`health <._health>` — solver health monitor: bounded residual
  histories, NaN/stagnation/divergence detectors emitting
  ``solver.anomaly`` events; :func:`last_solve_report` returns the most
  recent solve's forensics.
* :func:`events` / :func:`reset` / :func:`configure` / :func:`flush` —
  ring snapshot, state reset, sink redirection, sink flush.
* ``schema`` (module) — the event-kind table + ``validate`` /
  ``validate_jsonl`` used by tests and documented in docs/telemetry.md.

Enabled by ``SPARSE_TPU_TELEMETRY=1`` (or ``settings.telemetry = True``);
sink override via ``SPARSE_TPU_TELEMETRY_PATH`` / :func:`configure`.
The metrics registry alone is always on (plain int bumps — the plan
cache has counted that way since PR 2).
"""

from __future__ import annotations

from . import _budget as budget  # noqa: F401
from . import _cost as cost  # noqa: F401
from . import _health as health  # noqa: F401
from . import _history as history  # noqa: F401
from . import _metrics as metrics  # noqa: F401
from . import _schema as schema  # noqa: F401
from ._context import (  # noqa: F401
    current_tickets,
    new_ticket_id,
    ticket_scope,
)
from ._health import last_solve_report  # noqa: F401
from ._metrics import metrics_text  # noqa: F401
from ._recorder import (  # noqa: F401
    add_bytes,
    add_span,
    bytes_by_kind,
    configure,
    count,
    counters,
    dropped,
    enabled,
    events,
    flush,
    process_identity,
    record,
    session_info,
    sink_path,
)
from ._recorder import reset as _reset_recorder
from ._flight import (  # noqa: F401
    FlightRecorder,
    capture_now,
    flight,
    stop_flight,
)
from ._flight import state as flight_state  # noqa: F401
from ._history import Sampler  # noqa: F401
from ._history import start as start_history  # noqa: F401
from ._history import stop as stop_history  # noqa: F401
from ._history import state as history_state  # noqa: F401
from ._history import window as history_window  # noqa: F401
from ._profiler import capture_trace as profile_capture  # noqa: F401
from ._serve import AxonServer, serve, serving, stop_serving  # noqa: F401
from ._spans import Span, device_sync, span  # noqa: F401
from ._watchdog import (  # noqa: F401
    Rule,
    Watchdog,
    add_alert_hook,
    remove_alert_hook,
    stop_watchdog,
    watchdog,
)
from ._watchdog import state as watchdog_state  # noqa: F401
from ._summary import summary  # noqa: F401
from ._trace import export_trace, to_chrome_trace  # noqa: F401


def reset() -> None:
    """Clear the in-memory state: ring, counters, byte totals, span
    aggregates, drop count, the health monitor's solve reports and the
    program attribution table (the JSONL sink file is untouched — it is
    an append-only session log; a running exporter keeps serving). The
    always-on metrics families owned by other modules (plan cache,
    batch service) keep their values; reset those at their owners."""
    _reset_recorder()
    health.reset()
    cost.reset()


__all__ = [
    "add_alert_hook",
    "add_bytes",
    "add_span",
    "AxonServer",
    "budget",
    "history",
    "history_state",
    "history_window",
    "Sampler",
    "start_history",
    "stop_history",
    "bytes_by_kind",
    "capture_now",
    "configure",
    "cost",
    "count",
    "counters",
    "current_tickets",
    "device_sync",
    "dropped",
    "enabled",
    "events",
    "export_trace",
    "flight",
    "flight_state",
    "FlightRecorder",
    "flush",
    "health",
    "last_solve_report",
    "metrics",
    "metrics_text",
    "new_ticket_id",
    "process_identity",
    "profile_capture",
    "record",
    "remove_alert_hook",
    "session_info",
    "reset",
    "schema",
    "serve",
    "serving",
    "sink_path",
    "span",
    "Span",
    "stop_flight",
    "stop_serving",
    "stop_watchdog",
    "summary",
    "ticket_scope",
    "to_chrome_trace",
    "Rule",
    "Watchdog",
    "watchdog",
    "watchdog_state",
]
