"""Incident flight recorder: postmortem bundles captured at alert time.

The serving stack alerts live (:mod:`._watchdog` → ``/alerts``) but —
before this module — captured nothing at the moment of breach: an
operator paged by ``slo_miss_rate`` had only whatever JSONL happened to
survive, with the in-memory ring, the metrics levels and the live
session state all gone by the time anyone looked. The reference stack
gets exactly this from Legion's task-level profiler (Legate Sparse
SC'23, PAPERS.md §1); here the :class:`FlightRecorder` closes the loop
from *alert* to *evidence*: every watchdog ok → firing transition is
offered to the recorder (the ``_ALERT_HOOKS`` hook point in
:mod:`._watchdog`), which writes one rate-limited, count-bounded
**postmortem bundle** under ``results/axon/incidents/<ts>-<rule>/``:

``incident.json``
    the manifest: the triggering transition (rule, severity, sampled
    value, threshold), process identity + session clock base, the full
    watchdog rule state, the health monitor's last solve report,
    failover latches + fault-injection status, live session stats
    (``service.sessions_stats()``), the compiled-program cost table
    (:mod:`._cost`), and an env/config/mesh fingerprint — everything an
    operator (or ``scripts/axon_doctor.py``) needs to reconstruct the
    moment of breach.
``ring.jsonl``
    the recorder ring tail (newest ``ring_tail`` events), led by this
    process's ``session.start`` identity record — under the
    multi-controller sink split each process's bundle carries ITS ring
    and ITS identity block, same contract as ``records.<pid>.jsonl``.
``metrics.json``
    the always-on registry snapshot plus ``plan_cache.stats()``.
``trace.json``
    a Perfetto trace slice of the ring tail (``telemetry.export_trace``)
    — the per-ticket waterfalls of the requests in flight at breach.
``history.json``
    when the v7 history sampler (:mod:`._history`) is running: the last
    ten minutes of metric time series (raw + rollups) ending at the
    breach — how ``axon_doctor`` says when a regression *started*, not
    just that it fired.
``profile/`` (on-demand captures only)
    a ``jax.profiler`` trace of a short live window (:mod:`._profiler`).

Discipline (the satellite tests pin all three):

* **Off by default.** Without ``SPARSE_TPU_FLIGHT`` (or an explicit
  :func:`flight` call) the alert hook is one settings check — no
  filesystem touch, no allocation, no singleton.
* **Rate-limited.** Captures inside ``min_interval_s`` of the previous
  one are counted (``flight.suppressed``) and skipped — a flapping rule
  or a multi-rule storm produces ONE bundle per window, not a disk
  flood.
* **Count-bounded.** At most ``max_bundles`` bundles are retained;
  writing a new one prunes the oldest (``scripts/trim_records.py``
  additionally prunes committed results).

``scripts/axon_doctor.py`` is the stdlib-only analyzer over a bundle;
the live exporter serves :func:`state` on ``/incidents`` and manual
captures on ``/debug/capture``.
"""

from __future__ import annotations

import dataclasses
import json
import os
import shutil
import threading
import time

from ..config import settings
from . import _metrics, _recorder

__all__ = [
    "FlightRecorder",
    "bundles",
    "capture_now",
    "current",
    "flight",
    "on_alert_transition",
    "state",
    "stop_flight",
]

#: default incidents root: results/axon/incidents next to the repo root
#: (the same derivation as the recorder's default sink)
_DEFAULT_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "results",
    "axon",
    "incidents",
)

#: truthy spellings of SPARSE_TPU_FLIGHT that mean "default root"
_TRUTHY = ("1", "true", "yes", "on")

_LOCK = threading.Lock()
_RECORDER: "FlightRecorder | None" = None

_CAPTURES = "flight.captures"
_SUPPRESSED = _metrics.counter(
    "flight.suppressed",
    help="alert transitions whose bundle capture was rate-limited away",
)


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except (TypeError, ValueError):
        return str(v)


def _fingerprint() -> dict:
    """The env/config/mesh identity block of a bundle: which knobs and
    topology produced the incident. Every probe is best-effort — a
    fingerprint must never fail a capture."""
    out: dict = {
        "env": {
            k: v for k, v in sorted(os.environ.items())
            if k.startswith("SPARSE_TPU_")
            or k in ("JAX_PLATFORMS", "XLA_FLAGS", "JAX_ENABLE_X64")
        },
    }
    try:
        out["config"] = {
            f.name: _jsonable(getattr(settings, f.name))
            for f in dataclasses.fields(settings)
        }
    except Exception:
        pass
    try:
        import jax

        out["jax"] = str(jax.__version__)
        out["backend"] = str(jax.default_backend())
        out["devices"] = len(jax.devices())
    except Exception:
        pass
    try:
        from ..parallel import mesh as _mesh

        out["mesh"] = _mesh.mesh_fingerprint(_mesh.get_mesh())
    except Exception:
        pass
    return out


class FlightRecorder:
    """The incident capturer. Construct via :func:`flight` (or directly
    in tests); :meth:`on_alert` is what the watchdog hook calls,
    :meth:`capture` the underlying (and on-demand) bundle writer."""

    def __init__(self, root: str | None = None,
                 max_bundles: int | None = None,
                 min_interval_s: float = 30.0, ring_tail: int = 512):
        self.root = root or _DEFAULT_ROOT
        self.max_bundles = max(
            int(max_bundles if max_bundles is not None
                else settings.flight_max), 1,
        )
        self.min_interval_s = max(float(min_interval_s), 0.0)
        self.ring_tail = max(int(ring_tail), 1)
        self.captures = 0
        self.suppressed = 0
        self.last_capture = None  # monotonic instant of the last bundle
        self.last_bundle = None  # path of the last bundle written
        self._lock = threading.Lock()
        self._seq = 0

    # -- the hook entry -----------------------------------------------------
    def on_alert(self, transition: dict) -> str | None:
        """Capture a bundle for one alert transition; returns the bundle
        dir, or ``None`` when rate-limited (counted as suppressed)."""
        return self.capture(
            reason="alert",
            rule=str(transition.get("rule", "?")),
            transition=transition,
        )

    # -- capture ------------------------------------------------------------
    def capture(self, reason: str = "manual", rule: str | None = None,
                transition: dict | None = None,
                profile: bool = False,
                profile_seconds: float = 0.2) -> str | None:
        """Write one postmortem bundle (module docstring has the
        layout); returns its directory. Rate limiting applies to every
        reason — a manual ``/debug/capture`` inside the window is
        suppressed like an alert storm would be. Every write inside the
        bundle is individually best-effort: a failing probe shrinks the
        bundle, never kills the capture (and never the alert that
        triggered it)."""
        t0 = time.perf_counter()
        with self._lock:
            now = time.monotonic()
            if (
                self.last_capture is not None
                and now - self.last_capture < self.min_interval_s
            ):
                self.suppressed += 1
                _SUPPRESSED.inc()
                return None
            self.last_capture = now
            self._seq += 1
            seq = self._seq
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime())
        name = f"{stamp}.{seq:03d}-{rule or reason}"
        path = os.path.join(self.root, name)
        try:
            os.makedirs(path, exist_ok=True)
        except OSError:
            return None  # unwritable root: captures silently unavailable
        tail = _recorder.events()[-self.ring_tail:]
        self._write_ring(path, tail)
        self._write_metrics(path)
        self._write_trace(path, tail)
        self._write_history(path)
        profile_info = None
        if profile:
            from . import _profiler

            profile_info = _profiler.capture_trace(
                os.path.join(path, "profile"), seconds=profile_seconds,
            )
        self._write_manifest(
            path, reason=reason, rule=rule, transition=transition,
            events=len(tail), profile=profile_info,
            captured_ms=round((time.perf_counter() - t0) * 1e3, 3),
            tail=tail,
        )
        with self._lock:
            self.captures += 1
            self.last_bundle = path
        _metrics.counter(
            _CAPTURES,
            help="incident bundles written (rule label; 'manual' for "
            "on-demand captures)",
            rule=rule or reason,
        ).inc()
        _recorder.record(
            "flight.capture", reason=reason, rule=rule or "",
            dir=os.path.basename(path), events=len(tail),
        )
        self._prune()
        return path

    # -- bundle pieces (each individually best-effort) ----------------------
    def _write_ring(self, path: str, tail: list) -> None:
        try:
            with open(os.path.join(path, "ring.jsonl"), "w") as f:
                # lead with the identity record, same contract as a sink
                # file: a bundle is self-describing about WHICH process
                # (and which records.<pid>.jsonl) it came from
                f.write(
                    json.dumps(
                        _recorder._session_start_event(),
                        default=_recorder._jsonable,
                    ) + "\n"
                )
                for ev in tail:
                    f.write(
                        json.dumps(ev, default=_recorder._jsonable) + "\n"
                    )
        except Exception:
            pass

    def _write_metrics(self, path: str) -> None:
        payload: dict = {}
        try:
            payload["metrics"] = _metrics.snapshot()
        except Exception:
            pass
        try:
            from .. import plan_cache

            payload["plan_cache"] = plan_cache.stats()
        except Exception:
            pass
        try:
            with open(os.path.join(path, "metrics.json"), "w") as f:
                json.dump(payload, f, indent=1, sort_keys=True, default=str)
                f.write("\n")
        except Exception:
            pass

    def _write_trace(self, path: str, tail: list) -> None:
        try:
            from . import _trace

            _trace.export_trace(os.path.join(path, "trace.json"),
                                events=tail)
        except Exception:
            pass

    def _write_history(self, path: str) -> None:
        """The pre-incident time-series window: when the v7 history
        sampler is live, embed its last ten minutes (raw + rollups) so a
        bundle shows when the regression started, not just that it
        fired. Absent sampler -> absent file (no stub)."""
        try:
            from . import _history

            sampler = _history.current()
            if sampler is None:
                return
            sampler.flush()
            points = sampler.window(seconds=600.0)
            payload = {
                "schema": 1,
                "interval_s": sampler.interval_s,
                "window_s": 600.0,
                "points": points,
                "state": sampler.state(),
            }
            with open(os.path.join(path, "history.json"), "w") as f:
                json.dump(payload, f, default=str)
                f.write("\n")
        except Exception:
            pass

    def _write_manifest(self, path: str, reason: str, rule, transition,
                        events: int, profile, captured_ms: float,
                        tail: list | None = None) -> None:
        man: dict = {
            "schema": 1,
            "reason": reason,
            "rule": rule or "",
            "ts": time.time(),
            "iso": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
            "events": events,
            "captured_ms": captured_ms,
        }
        if transition:
            man["transition"] = {
                k: _jsonable(v) for k, v in transition.items()
            }
        if profile:
            man["profile"] = profile
        try:
            man["process"] = dict(_recorder.process_identity())
            man["session"] = dict(_recorder.session_info())
        except Exception:
            pass
        try:
            from . import _watchdog

            man["watchdog"] = _watchdog.state()
        except Exception:
            pass
        try:
            from . import _health

            man["health"] = _health.last_solve_report() or {}
        except Exception:
            pass
        try:
            from ..resilience import failover, faults

            man["failover_latches"] = failover.latches()
            man["faults"] = {
                "active": bool(faults.ACTIVE),
                "spec": settings.faults,
                "fires": faults.stats(),
            }
        except Exception:
            pass
        try:
            from ..batch import service

            man["sessions"] = service.sessions_stats()
        except Exception:
            pass
        # the elastic-mesh transition (ISSUE 20): a bundle captured near
        # a topology change embeds the remesh events from its ring tail
        # — old/new fingerprints, trigger reason, lanes migrated — so
        # axon_doctor names the transition without re-reading the ring
        try:
            remeshes = [
                {k: _jsonable(v) for k, v in ev.items()
                 if k in ("kind", "old", "new", "reason", "requeued",
                          "replayed", "devices", "wall_ms", "ts")}
                for ev in (tail or ())
                if ev.get("kind") in ("fleet.remesh", "fleet.remesh_failed")
            ]
            if remeshes:
                man["remesh"] = remeshes[-8:]
        except Exception:
            pass
        try:
            from . import _cost

            man["programs"] = _cost.programs()
        except Exception:
            pass
        man["fingerprint"] = _fingerprint()
        try:
            with open(os.path.join(path, "incident.json"), "w") as f:
                json.dump(man, f, indent=1, sort_keys=True, default=str)
                f.write("\n")
        except Exception:
            pass

    def _prune(self) -> None:
        """Retention bound: keep the newest ``max_bundles`` bundles
        (names sort chronologically — the stamp.seq prefix)."""
        try:
            names = sorted(
                n for n in os.listdir(self.root)
                if os.path.isdir(os.path.join(self.root, n))
            )
        except OSError:
            return
        for n in names[: max(len(names) - self.max_bundles, 0)]:
            shutil.rmtree(os.path.join(self.root, n), ignore_errors=True)

    # -- views --------------------------------------------------------------
    def state(self) -> dict:
        """JSON-friendly recorder state (the ``/incidents`` payload)."""
        with self._lock:
            out = {
                "enabled": True,
                "root": self.root,
                "max_bundles": self.max_bundles,
                "min_interval_s": self.min_interval_s,
                "captures": self.captures,
                "suppressed": self.suppressed,
                "last_bundle": (
                    os.path.basename(self.last_bundle)
                    if self.last_bundle else None
                ),
            }
        out["bundles"] = bundles(self.root)
        return out


def bundles(root: str | None = None) -> list:
    """Headline rows of every bundle under ``root`` (newest first):
    name, rule, reason, iso timestamp, event count — what ``/incidents``
    lists and ``axon_doctor --latest`` resolves against."""
    root = root or _DEFAULT_ROOT
    rows = []
    try:
        names = sorted(os.listdir(root), reverse=True)
    except OSError:
        return rows
    for n in names:
        man_path = os.path.join(root, n, "incident.json")
        if not os.path.isfile(man_path):
            continue
        row = {"name": n}
        try:
            man = json.load(open(man_path))
            for k in ("rule", "reason", "iso", "ts", "events"):
                if k in man:
                    row[k] = man[k]
        except (OSError, json.JSONDecodeError, ValueError):
            row["corrupt"] = True
        rows.append(row)
    return rows


# ---------------------------------------------------------------------------
# the process singleton (what the watchdog hook and /incidents use)
# ---------------------------------------------------------------------------
def _root_from_settings() -> str | None:
    v = (settings.flight or "").strip()
    if not v:
        return None
    if v.lower() in _TRUTHY:
        return _DEFAULT_ROOT
    return v


def flight(root: str | None = None, **kw) -> FlightRecorder:
    """Get-or-create the process flight recorder. An existing instance
    is returned as-is (``stop_flight()`` first to reconfigure); with no
    ``root`` the settings resolution applies (default incidents dir)."""
    global _RECORDER
    with _LOCK:
        if _RECORDER is None:
            _RECORDER = FlightRecorder(
                root=root or _root_from_settings() or _DEFAULT_ROOT, **kw
            )
        return _RECORDER


def current() -> FlightRecorder | None:
    """The live process recorder, or ``None``."""
    return _RECORDER


def stop_flight() -> None:
    """Drop the process recorder (bundles on disk are untouched)."""
    global _RECORDER
    with _LOCK:
        _RECORDER = None


def on_alert_transition(transition: dict) -> str | None:
    """The watchdog hook target: capture a bundle for one alert
    transition. Off path (no recorder AND ``SPARSE_TPU_FLIGHT`` unset)
    is a single settings check — no filesystem, no singleton."""
    fr = _RECORDER
    if fr is None:
        if _root_from_settings() is None:
            return None  # disabled by default: nothing happens
        fr = flight()
    return fr.on_alert(transition)


def capture_now(reason: str = "manual", profile: bool = True,
                profile_seconds: float = 0.2) -> str | None:
    """On-demand bundle (the ``/debug/capture`` endpoint): same layout
    as an alert capture plus a ``jax.profiler`` trace of a short live
    window. Creates the recorder if flight is enabled OR forced by the
    explicit call (a manual capture is an operator action — it works
    even when automatic capture is off)."""
    return flight().capture(
        reason=reason, profile=profile, profile_seconds=profile_seconds,
    )


def state() -> dict:
    """The ``/incidents`` payload: recorder state + bundle listing, or a
    disabled stub (which still lists any bundles already on disk at the
    settings root, so a restarted exporter can show past incidents)."""
    fr = _RECORDER
    if fr is not None:
        return fr.state()
    root = _root_from_settings()
    return {
        "enabled": False,
        "root": root,
        "captures": 0,
        "suppressed": 0,
        "bundles": bundles(root) if root else [],
    }
