"""Compile-time cost attribution for plan-cached programs.

The plan cache makes the serving path's compile economics legible as
*counts* (one miss per bucket, ever); this module makes them legible as
*costs*. At the moment a program is built on a cache miss,
:func:`attribute` ahead-of-time lowers and compiles it (``jax.jit``'s
AOT surface), measures the wall-clock compile duration, and captures
XLA's own ``cost_analysis()`` (flops, bytes accessed) and
``memory_analysis()`` (peak temp/argument/output HBM) for the compiled
executable. Each capture:

* registers into a bounded in-process program table (:func:`programs`)
  — the ``/session`` serving endpoint and ``scripts/axon_report.py``'s
  achieved-vs-roofline table read it;
* bumps always-on metrics (``plan_cache.compiles`` /
  ``plan_cache.compile_seconds`` counters, per-program
  ``plan_cache.program_*`` gauges) so a Prometheus scrape sees the
  session's cold-start budget without the event log;
* emits one ``plan_cache.compile`` event (telemetry on), which is what
  the report joins against measured ``batch.dispatch`` solve wall time.

Everything is best-effort by construction: backends without cost
analysis, non-jitted programs (the GMRES host-driven closure), or an
AOT path that rejects the arguments all degrade to "no analysis, keep
the original callable" — attribution must never break a solve.
"""

from __future__ import annotations

import threading
import time

from . import _metrics, _recorder

#: bounded program table: program key -> attribution dict
_PROGRAMS: dict = {}
_PROGRAMS_MAX = 256
_LOCK = threading.RLock()

# registered at import so the cold-start budget is present in
# metrics_text() from the first scrape
_COMPILES = _metrics.counter(
    "plan_cache.compiles", help="programs compiled (plan-cache misses "
    "that built an executable)",
)
_COMPILE_SECONDS = _metrics.counter(
    "plan_cache.compile_seconds",
    help="total wall-clock seconds spent building (pack) and compiling "
    "plan-cached programs (the session's cold-start budget)",
)


def _cost_dict(compiled):
    """XLA cost analysis of a compiled executable as a flat dict
    (handles the list-of-dict shape older jax versions return)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca if isinstance(ca, dict) else {}


def _memory_dict(compiled):
    """Peak-memory attribution from ``memory_analysis()`` (attribute
    names per jax's ``CompiledMemoryStats``); empty when unsupported."""
    try:
        ma = compiled.memory_analysis()
    except Exception:
        return {}
    if ma is None:
        return {}
    out = {}
    for name, key in (
        ("temp_size_in_bytes", "temp_bytes"),
        ("argument_size_in_bytes", "arg_bytes"),
        ("output_size_in_bytes", "out_bytes"),
        ("generated_code_size_in_bytes", "code_bytes"),
    ):
        v = getattr(ma, name, None)
        if isinstance(v, (int, float)) and not isinstance(v, bool) and v >= 0:
            out[key] = int(v)
    if out:
        out["peak_bytes"] = (
            out.get("temp_bytes", 0) + out.get("arg_bytes", 0)
            + out.get("out_bytes", 0)
        )
    return out


class _Program:
    """A plan-cache entry wrapping an AOT-compiled executable with the
    original jitted callable as fallback: if the compiled object ever
    rejects a call (argument layout drift), the entry permanently
    reverts to the jit path — same results, just a recompile."""

    __slots__ = ("fn", "compiled")

    def __init__(self, fn, compiled):
        self.fn = fn
        self.compiled = compiled

    def __call__(self, *args):
        if self.compiled is not None:
            try:
                return self.compiled(*args)
            except Exception:
                self.compiled = None
        return self.fn(*args)


def _register(program: str, info: dict) -> None:
    with _LOCK:
        if program not in _PROGRAMS and len(_PROGRAMS) >= _PROGRAMS_MAX:
            _PROGRAMS.pop(next(iter(_PROGRAMS)))
        _PROGRAMS[program] = info
    _COMPILES.inc()
    # cold-start budget = pack + compile, matching axon_report's
    # cold_start_s so /session and the report quote the same number
    _COMPILE_SECONDS.add(
        float(info.get("compile_s") or 0.0)
        + float(info.get("pack_s") or 0.0)
    )
    for key, metric in (
        ("flops", "plan_cache.program_flops"),
        ("bytes", "plan_cache.program_bytes"),
        ("peak_bytes", "plan_cache.program_peak_bytes"),
        ("compile_s", "plan_cache.program_compile_s"),
    ):
        v = info.get(key)
        if isinstance(v, (int, float)) and not isinstance(v, bool):
            _metrics.gauge(metric, program=program).set(float(v))
    _recorder.record("plan_cache.compile", **info)


def attribute(program: str, fn, args, pack_s: float | None = None,
              **labels):
    """Attribute one freshly built program: AOT-compile ``fn`` on the
    concrete ``args`` when it exposes the jit AOT surface, capture
    compile wall-clock + cost/memory analysis, and return
    ``(callable, info)`` — the callable to cache in place of ``fn``
    (the compiled wrapper, or ``fn`` itself when AOT is unavailable)
    plus the attribution dict. ``labels`` (solver, bucket, dtype, n,
    nnz, ...) ride into the event, the table, and the report join."""
    info = {"program": program, **labels}
    if pack_s is not None:
        info["pack_s"] = round(float(pack_s), 6)
    lower = getattr(fn, "lower", None)
    out = fn
    if lower is not None:
        try:
            lowered = lower(*args)
            t0 = time.perf_counter()
            compiled = lowered.compile()
            info["compile_s"] = round(time.perf_counter() - t0, 6)
            ca = _cost_dict(compiled)
            flops = ca.get("flops")
            if isinstance(flops, (int, float)) and flops >= 0:
                info["flops"] = float(flops)
            nbytes = ca.get("bytes accessed")
            if isinstance(nbytes, (int, float)) and nbytes >= 0:
                info["bytes"] = float(nbytes)
            info.update(_memory_dict(compiled))
            out = _Program(fn, compiled)
        except Exception:
            # AOT rejected (dynamic-shape program, experimental backend):
            # the jit path still compiles lazily on first call — record
            # the pack-only attribution and move on
            info.pop("compile_s", None)
    _register(program, info)
    return out, info


def record_pack(program: str, pack_s: float, **labels) -> None:
    """Attribution for a host-side prepare with no executable of its own
    (operator auto-warm at ``make_linear_operator``, the GMRES closure's
    pattern pack): wall-clock only, same table/event/metrics plumbing."""
    _register(
        program,
        {"program": program, "pack_s": round(float(pack_s), 6), **labels},
    )


def note_device_time(program: str, host_ms: float,
                     device_ms: float) -> None:
    """Fold one sampled timed dispatch (ISSUE 12, :mod:`._profiler`)
    into the program table: measured device/host milliseconds accumulate
    next to the analytic flops/bytes so ``/session`` and
    ``axon_report``'s roofline table gain a *measured* ``device_ms``
    column. A program the table no longer holds (evicted, or compiled by
    an earlier process) gets a minimal measured-only row. Under
    streaming dispatch (ISSUE 13) the sample arrives at the bucket's
    deferred retire and ``device_ms`` is its completion latency at the
    dispatch-return boundary — see the :mod:`._profiler` docstring."""
    with _LOCK:
        p = _PROGRAMS.get(program)
        if p is None:
            if len(_PROGRAMS) >= _PROGRAMS_MAX:
                _PROGRAMS.pop(next(iter(_PROGRAMS)))
            p = _PROGRAMS[program] = {"program": program}
        p["device_ms_total"] = round(
            p.get("device_ms_total", 0.0) + float(device_ms), 6
        )
        p["host_ms_total"] = round(
            p.get("host_ms_total", 0.0) + float(host_ms), 6
        )
        p["device_samples"] = p.get("device_samples", 0) + 1
        p["device_ms_mean"] = round(
            p["device_ms_total"] / p["device_samples"], 6
        )


def programs() -> dict:
    """Snapshot of the program attribution table
    (``{program: {compile_s, flops, bytes, peak_bytes, ...}}``)."""
    with _LOCK:
        return {k: dict(v) for k, v in _PROGRAMS.items()}


def total_compile_s() -> float:
    """The session's cold-start budget so far: total wall-clock seconds
    spent compiling plan-cached programs (always-on counter)."""
    return float(_COMPILE_SECONDS.value)


def reset() -> None:
    """Clear the program table (tests); the always-on counters keep
    their values like every other registry-owned metric."""
    with _LOCK:
        _PROGRAMS.clear()
