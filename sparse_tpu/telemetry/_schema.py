"""Event schema: the kinds the instrumentation emits and a validator.

The schema is deliberately open — unknown kinds and extra fields are
forward-compatible by design (a new instrumentation site must not break
old consumers) — but every event carries ``kind`` + ``ts``, and known
kinds carry their required fields. ``docs/telemetry.md`` documents the
same table for human consumers; tests validate emitted events against
this module so the doc, the code and the JSONL stay in sync.
"""

from __future__ import annotations

#: fields every event carries (added by the recorder itself)
BASE_FIELDS = frozenset({"kind", "ts"})

#: required fields per known kind (beyond BASE_FIELDS)
KINDS: dict[str, frozenset] = {
    # -- solvers (linalg.py) ------------------------------------------------
    # one per iteration (host/fused paths) or per conv-test chunk /
    # restart cycle; resid2 = ||r||^2 in the solve dtype where available
    "solver.iter": frozenset({"solver", "iter"}),
    # one per completed solve, every path
    "solver.solve": frozenset({"solver", "iters", "path"}),
    # a health-monitor detection (telemetry/_health.py): reason is
    # 'nonfinite' | 'divergence' | 'stagnation' | 'breakdown'; batched
    # solves add the lane index; at most one event per (reason, lane)
    # per solve
    "solver.anomaly": frozenset({"solver", "reason"}),
    # -- resilience (sparse_tpu.resilience) ---------------------------------
    # one injected fault firing (faults.py): site is
    # 'matvec' | 'pallas' | 'dispatch' | 'chunk', fault the clause kind
    "fault.injected": frozenset({"site", "fault"}),
    # the recovery policy engine retrying a solve: reason is the health
    # verdict ('nonfinite' | 'breakdown' | 'stagnation' | 'preempt'),
    # action the ladder step ('restart' | 'escalate' | 'rollback' |
    # 'clean'), solver the one the NEXT attempt runs
    "solver.retry": frozenset({"solver", "attempt", "reason"}),
    # a recovered solve: converged after >= 1 retry
    "solver.recovered": frozenset({"solver", "attempts"}),
    # attempt/deadline budget exhausted without convergence
    "solver.giveup": frozenset({"solver", "attempts"}),
    # a probe reinstated a previously failed-over Pallas kernel
    "kernel.reinstate": frozenset({"kernel"}),
    # CheckpointManager.load() skipped a corrupt/truncated .npz
    "checkpoint.corrupt": frozenset({"path"}),
    # -- kernels (kernels/dia_spmv.py) -------------------------------------
    # a completed tile-autotune race: timings_us maps probed tile -> best
    # seconds-per-SpMV in microseconds; clock is 'compiled' | 'host'
    "autotune.probe": frozenset({"tile", "shape", "timings_us"}),
    # an autotune decision that did NOT probe (gate/cache) — never cached
    # as if it were a probe result
    "autotune.result": frozenset({"tile", "probed"}),
    # a Pallas kernel permanently failing over to the XLA formulation
    "kernel.failover": frozenset({"kernel", "error"}),
    # a structural fast path silently unavailable at runtime (e.g. banded
    # detection's host fetch failing on an experimental backend), with the
    # path actually taken in `to` — the perf-cliff breadcrumb
    "coverage.fallback": frozenset({"op", "reason"}),
    # -- distribution (parallel/) ------------------------------------------
    # measured collective volume of a compiled program (parallel/comm.py
    # trace-time accounting x observed executions), reconciled against the
    # analytic model when one exists (model_bytes / divergence_pct);
    # exact=False marks capacity-accounted ragged exchanges
    "comm.measured": frozenset({"site", "bytes"}),
    # structural comm model of a freshly sharded operator (per-SpMV cost)
    "comm.spmv": frozenset({"bytes", "mode", "S"}),
    # whole-solve collective volume of a distributed CG run
    "comm.cg": frozenset({"bytes", "S", "iters"}),
    # 2-D SpGEMM replication + shuffle volumes
    "comm.spgemm2d": frozenset({"bytes", "grid"}),
    # samplesort exchange volumes (from the host-visible send matrix)
    "comm.sort": frozenset({"bytes", "S"}),
    # -- batched solves (sparse_tpu.batch) ----------------------------------
    # one per bucket a SolveSession dispatches: real lane count, padded
    # bucket size, pad waste, queue latency and per-lane iteration stats
    "batch.dispatch": frozenset({"solver", "batch", "bucket"}),
    # one per completed batched Krylov solve (any entry point); B is the
    # lane count, iters_max the slowest lane's iteration count
    "batch.solve": frozenset({"solver", "B", "iters_max"}),
    # unconverged/nonfinite lanes requeued into a fallback bucket
    # (safer solver/dtype — docs/resilience.md)
    "batch.requeue": frozenset({"solver", "lanes"}),
    # a bucket degraded to per-lane eager solves (compiled path
    # unavailable); reason carries the triggering error
    "batch.degraded": frozenset({"solver", "reason"}),
    # tickets hit by their per-ticket deadline: stage 'dispatch' =
    # failed while still queued (TicketDeadlineError), stage 'readback'
    # = the streaming pipeline skipped a requeue for lanes whose budget
    # lapsed while their bucket was in flight (they keep their result)
    "batch.deadline": frozenset({"solver", "lanes"}),
    # one per bucket admitted to the streaming in-flight window
    # (ISSUE 13): the window depth after the enqueue, its capacity
    # (SPARSE_TPU_INFLIGHT), the program key and real lane count
    "batch.inflight": frozenset({"depth", "capacity"}),
    # submit-time admission control engaged (max_queue_depth reached):
    # mode 'reject' (AdmissionError raised) or 'block' (submit drove
    # the pipeline until below the threshold; waited_ms carries how
    # long)
    "batch.admission": frozenset({"mode", "depth"}),
    # the per-ticket TERMINAL event: one per submitted system per flush
    # resolution, carrying the final state ('done' | 'failed'), the
    # end-to-end latency and the per-phase breakdown (queue/pack/compile/
    # solve/readback ms) — the record a ticket trace ends on
    "batch.ticket": frozenset({"ticket", "state"}),
    # -- fleet (sparse_tpu.fleet, the mesh-sharded serving tier) ------------
    # one per mesh-sharded bucket dispatch: the strategy the policy
    # picked ('batch' | 'row'), mesh size S, bucket/lane counts, the
    # mesh fingerprint, and per-device real-lane counts (device_lanes)
    "fleet.dispatch": frozenset({"strategy", "S", "bucket"}),
    # per-device detail of one sharded dispatch: real lanes this device
    # served out of its bucket_lanes-slot block (occupancy numerator)
    "fleet.shard": frozenset({"device", "lanes"}),
    # one executed live topology migration (fleet/elastic.py, ISSUE 20):
    # old/new mesh fingerprints, the trigger reason ('fault' |
    # 'dispatch_error' | 'manual'), lanes requeued through the
    # migration, manifest programs warm-replayed against the new
    # topology, and the quiesce+re-plan wall clock. Counts into the
    # always-on fleet.remeshes{outcome} counter.
    "fleet.remesh": frozenset({"old", "new", "reason"}),
    # a remesh that did NOT re-plan: reason 'flap_guard' (the bounded
    # SPARSE_TPU_REMESH_RETRIES budget latched — the session pinned to
    # the single-device strategy) or 'noop' is never emitted (identical
    # topology returns silently)
    "fleet.remesh_failed": frozenset({"reason"}),
    # -- preconditioners (sparse_tpu.precond, ISSUE 14) ---------------------
    # one pattern-level preconditioner build (diag/block extraction map,
    # ILU(0)/IC(0) symbolic factorization): precond is the kind,
    # build_ms the host wall clock — cadence is exactly one per
    # (pattern, kind) per vault (the plan-cache build closure)
    "precond.build": frozenset({"precond", "n"}),
    # one preconditioned bucket dispatch: the resolved kind actually
    # applied inside the compiled program, with the lane count (numeric
    # factorization happens in-program, so this is the host-side record
    # that it ran)
    "precond.apply": frozenset({"precond", "lanes"}),
    # -- mixed precision (sparse_tpu.mixed, ISSUE 15) -----------------------
    # the promote_dtype rung fired: an anomalous reduced-precision
    # bucket pinned its (pattern, solver, bucket, dtype) group to
    # 'exact' and requeued the failed lanes at full precision; reason
    # is 'nonfinite' | 'unconverged', from_policy the reduced policy
    # the bucket ran under. Pairs with a batch.requeue event carrying
    # action='promote_dtype'. Counts into the always-on
    # mixed.promotions{reason} metric; IR sweep totals ride the
    # always-on mixed.ir_outer_iters counter.
    "mixed.promote": frozenset({"reason", "lanes"}),
    # -- plan cache (sparse_tpu.plan_cache / telemetry/_cost.py) ------------
    # one per compiled (or host-packed) plan-cached program: wall-clock
    # compile/pack seconds plus XLA cost/memory analysis when available
    # (flops, bytes, peak_bytes) — the roofline join key is `program`
    "plan_cache.compile": frozenset({"program"}),
    # -- vault (sparse_tpu.vault, the persistent plan-cache tier) -----------
    # one artifact write attempt: artifact is the codec kind ('pattern' |
    # 'sell_pattern' | 'prepared_csr' | 'prepared_dia'), ok whether the
    # atomic write landed (False = cleaned up, vault unchanged)
    "vault.store": frozenset({"artifact", "ok"}),
    # one successful verified artifact load (disk-tier hit)
    "vault.load": frozenset({"artifact", "hit"}),
    # a verify failure: the file was moved into the quarantine sidecar;
    # reason is the verify-ladder step that failed ('bad-magic' |
    # 'bad-header' | 'stale-format' | 'stale-jax' | 'key-mismatch' |
    # 'truncated' | 'checksum' | 'decode-error' | 'expect-*' |
    # 'manifest')
    "vault.quarantine": frozenset({"artifact", "reason"}),
    # a size-budgeted LRU sweep that evicted artifacts
    "vault.gc": frozenset({"evicted"}),
    # a SolveSession replayed the warm-start manifest on construction:
    # entries read, programs successfully replayed
    "vault.replay": frozenset({"entries", "programs"}),
    # -- loadgen / watchdog (sparse_tpu.loadgen, telemetry/_watchdog.py) ----
    # one completed load run: the canonical trace spec, arrival count,
    # offered/achieved req/s, latency percentiles, SLO-miss rate and the
    # weighted fairness index — what axon_report's `load` rollup reads
    "loadgen.trace": frozenset({"trace", "arrivals"}),
    # a watchdog rule transitioned ok -> firing: the rule name, its
    # severity, the sampled value and the trigger threshold it breached
    "watchdog.alert": frozenset({"rule", "severity"}),
    # the matching firing -> ok transition (hysteresis satisfied), with
    # the clearing value and how long the alert was active
    "watchdog.clear": frozenset({"rule"}),
    # -- incident flight recorder (telemetry/_flight.py, ISSUE 12) ----------
    # one postmortem bundle written: reason is 'alert' (a watchdog
    # transition captured it) or 'manual' (/debug/capture), rule the
    # triggering rule name ('' for manual), dir the bundle directory
    # basename under results/axon/incidents/
    "flight.capture": frozenset({"reason", "dir"}),
    # one on-demand jax.profiler trace window (telemetry/_profiler.py):
    # ok whether the capture landed; failed captures carry `error`
    "profile.capture": frozenset({"ok", "dir"}),
    # -- autopilot (sparse_tpu.autopilot, ISSUE 16) -------------------------
    # one measured experiment: the tuner dispatched `arm`'s candidate
    # spec for group `group` and scored the retired ticket batch
    "autopilot.trial": frozenset({"group", "arm"}),
    # an arm eliminated mid-schedule (SLO-guard breach or a halving
    # round's worst half) — reason says which
    "autopilot.abort": frozenset({"group", "arm", "reason"}),
    # a group converged: exploration closed, `arm` is the pinned
    # PolicyDecision (persisted as an `autopilot_policy` vault artifact)
    "autopilot.converge": frozenset({"group", "arm"}),
    # exploration re-opened on a pinned group: reason is the drift
    # signal ('watchdog:<rule>', 'promote:<reason>', 'drift', or a
    # chaos-drill tag)
    "autopilot.reopen": frozenset({"group", "reason"}),
    # a restart restored a persisted decision from the vault — the
    # group serves tuned from its first request, zero trials
    "autopilot.restore": frozenset({"group", "arm"}),
    # -- ingest (sparse_tpu.ingest, ISSUE 18) -------------------------------
    # one arrival admitted onto the background onboarding queue: the
    # ticket id, a source label (path / array type) and the queue depth
    # at admission (the backpressure signal)
    "ingest.arrive": frozenset({"ticket", "source", "queue_depth"}),
    # one COO->CSR sort pass of the ingest data plane: matrix rows,
    # deduped nnz, raw entries in, mesh shards, which route ran
    # (fast_path = single-device jax.lax.sort; otherwise the sharded
    # samplesort whose collective volume lands in comm.sort) and wall ms
    "ingest.sort": frozenset(
        {"rows", "nnz", "shards", "entries", "fast_path", "wall_ms"}
    ),
    # the fingerprint decision for one arrival: hit=True dedups onto an
    # existing pattern (zero new compiles — the whole program-key chain
    # is already warm); fingerprint is the structure key's short prefix
    "ingest.dedup": frozenset({"ticket", "hit", "fingerprint"}),
    # one onboarding lifecycle transition: state is 'retry' (an attempt
    # failed, the bounded worker goes again), 'ready' (terminal ok) or
    # 'failed' (terminal, after retries); wall_ms measures from arrival
    "ingest.onboard": frozenset({"ticket", "state", "wall_ms"}),
    # the per-arrival TERMINAL event (Axon v7 satellite), mirroring
    # batch.ticket: one per submitted arrival at resolution, carrying
    # the final state ('ready' | 'failed'), the end-to-end onboarding
    # latency and — tenant-tagged arrivals — the tenant label. The
    # always-on ingest.ticket_latency{state} histogram carries the same
    # latencies.
    "ingest.ticket": frozenset({"ticket", "state", "latency_ms"}),
    # -- SLO error budgets (telemetry/_budget.py, Axon v7) ------------------
    # a burn-rate rule's window pair read past its trigger for a tenant
    # ('aggregate' = every ticket): rate-limited breadcrumb recording
    # WHEN the budget started burning (the watchdog.alert that may
    # follow carries the hysteresis-filtered transition)
    "budget.burn": frozenset({"rule", "tenant", "burn"}),
    # -- generic ------------------------------------------------------------
    # one per process per sink file, written before the first event: the
    # controller's identity (process_index/pid/process_count, device
    # count, backend) plus the session clock base — wall-clock `epoch`
    # and the `mono`tonic reading at that instant — that
    # scripts/axon_merge.py uses to clock-align per-process logs
    "session.start": frozenset({"epoch", "mono", "pi", "pid"}),
    "span": frozenset({"name", "dur_s"}),
    # bench.py session record (always written by a bench run, even when
    # the TPU probe timed out)
    "bench.session": frozenset({"status"}),
    # a bench probe subprocess killed by its watchdog (used to be a bare
    # stderr line — ISSUE 6 satellite); the session record's `timeouts`
    # field carries the same entries
    "bench.probe_timeout": frozenset({"probe"}),
}


def validate(event: dict) -> list:
    """Return a list of problems (empty = schema-valid).

    Unknown kinds validate against BASE_FIELDS only (forward-compat);
    known kinds additionally require their fields. ``ts`` must be a
    positive number, ``kind`` a non-empty string.
    """
    problems = []
    kind = event.get("kind")
    if not isinstance(kind, str) or not kind:
        problems.append("missing/empty kind")
    ts = event.get("ts")
    if not isinstance(ts, (int, float)) or isinstance(ts, bool) or ts <= 0:
        problems.append("missing/invalid ts")
    required = KINDS.get(kind, frozenset())
    for f in sorted(required):
        if f not in event:
            problems.append(f"{kind}: missing required field {f!r}")
    b = event.get("bytes")
    if b is not None and (
        isinstance(b, bool) or not isinstance(b, (int, float)) or b < 0
    ):
        problems.append(f"{kind}: bytes must be a non-negative number")
    return problems


def validate_jsonl(path: str) -> list:
    """Validate every telemetry event line of a JSONL file; returns
    ``[(lineno, problem), ...]``. Lines without a ``kind`` field (e.g.
    bench.py hardware metric records sharing the session log) are
    skipped — the two record families coexist in records.jsonl by
    contract."""
    import json

    problems = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                problems.append((i, "not json"))
                continue
            if not isinstance(ev, dict) or "kind" not in ev:
                continue  # a bench metric record, not a telemetry event
            for p in validate(ev):
                problems.append((i, p))
    return problems
