"""Scoped wall-clock + device-sync timers (``span("cg.iter")``).

Trace safety is the defining constraint: library code wraps hot paths
that are routinely re-entered under ``jit``/``vmap``/``scan`` tracing,
where (a) wall-clock around tracer ops measures trace construction, not
execution, and (b) a ``block_until_ready`` on a tracer raises. A span
therefore degrades to a shared no-op object whenever telemetry is
disabled OR a trace is active (``utils.in_trace``) — no allocation on
the disabled path, no tracer leaks on the traced path.

Device sync discipline: ``block_until_ready`` runs only at span exit and
only on values handed to the span (``sync=...``) — never injected into
the middle of user computations.
"""

from __future__ import annotations

import time

from ..config import settings
from . import _metrics, _recorder

# Failed best-effort device syncs used to vanish silently (ISSUE 12
# satellite): a backend erroring inside block_until_ready is exactly the
# kind of degradation an operator should see. Always-on counter,
# surfaced on /healthz.
_SYNC_ERRORS = _metrics.counter(
    "telemetry.span_sync_errors",
    help="best-effort device syncs (span exit / device_sync) that "
    "raised — silent device errors surfacing",
)


class _NullSpan:
    """Shared disabled/traced span: every method is a no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def annotate(self, **fields):
        return self

    def set_sync(self, value):
        return value


_NULL = _NullSpan()


class Span:
    """One timed scope. Use via :func:`span`; not constructed directly."""

    __slots__ = ("name", "fields", "_t0", "_sync", "emit")

    def __init__(self, name: str, fields: dict, sync, emit: bool):
        self.name = name
        self.fields = fields
        self._sync = sync
        self.emit = emit
        self._t0 = None

    def annotate(self, **fields):
        """Attach fields to the span's event after entry (e.g. results
        computed inside the scope)."""
        self.fields.update(fields)
        return self

    def set_sync(self, value):
        """Register a device value to block on at span exit; returns the
        value unchanged so call sites stay expression-shaped."""
        self._sync = value
        return value

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, exc_type, exc, tb):
        # the device sync stays best-effort on BOTH paths: a span exiting
        # on an exception still blocks on work it registered (the timing
        # is recorded either way, with the exception type in `error`)
        if self._sync is not None:
            try:
                import jax

                jax.block_until_ready(self._sync)
            except Exception:
                # sync stays best-effort (the wall clock still stands),
                # but the failure is counted — see _SYNC_ERRORS
                _SYNC_ERRORS.inc()
        dur = time.perf_counter() - self._t0
        _recorder.add_span(self.name, dur)
        if self.emit:
            _recorder.record(
                "span",
                name=self.name,
                dur_s=round(dur, 9),
                **({"error": exc_type.__name__} if exc_type else {}),
                **self.fields,
            )
        return False


def span(name: str, sync=None, emit: bool = True, **fields):
    """Scoped timer: ``with span("cg.iter"): ...``.

    Returns a shared no-op context when telemetry is disabled or a jax
    trace is active (see module docstring). When live, records the
    duration into the p50/p95 aggregates and (``emit=True``) emits a
    ``span`` event. ``sync`` is an optional array/pytree blocked on at
    exit so device work attributes to the span rather than a later
    fence; pass ``emit=False`` for hot scopes that should aggregate
    without flooding the event log.
    """
    if not settings.telemetry:
        return _NULL
    from ..utils import in_trace

    if in_trace():
        return _NULL
    return Span(name, fields, sync, emit)


def device_sync(value):
    """Block on ``value`` when telemetry is enabled outside a trace —
    the free-standing boundary fence for code not using spans. Returns
    ``value`` unchanged; a pure pass-through when disabled/traced."""
    if not settings.telemetry:
        return value
    from ..utils import in_trace

    if in_trace():
        return value
    try:
        import jax

        jax.block_until_ready(value)
    except Exception:
        _SYNC_ERRORS.inc()
    return value
