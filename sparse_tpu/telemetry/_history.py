"""Time-series history store (Axon v7): the metrics registry over time.

Every other Axon surface observes an *instant* — ``metrics_text()`` is a
live snapshot, the watchdog fires on current values, ``axon_report``
reads one session log. This module adds the time dimension: a
low-overhead daemon :class:`Sampler` periodically scrapes the always-on
registry (:func:`._metrics.snapshot`) into

* **bounded in-memory rings** at three resolutions — raw samples at the
  scrape interval plus 10x and 60x rollups carrying per-series
  ``[min, max, mean, last]`` — the windows ``/dash``, the flight
  recorder and the budget engine read without touching disk; and
* **append-only on-disk segments** under ``results/axon/history/`` —
  each committed segment is written ATOMICALLY (per-process tmp name +
  fsync + ``os.replace``, the vault's idiom) so a crash can tear at
  most the not-yet-committed tail of the active segment, never a
  committed file. Loading is verify-then-load: a segment whose header
  is missing/alien is moved into ``quarantine/`` and skipped
  (degrade, don't die); a torn trailing line is dropped and the valid
  prefix kept. Retention is byte-capped: rotation-time GC deletes
  oldest segments past ``history_cap_mb``.

Zero overhead when off (the default): :func:`maybe_start` is a single
``settings.history`` attribute check, no thread exists, nothing touches
the filesystem, and program keys / jaxprs / host-sync counts are
byte-identical (pinned by ``tests/test_history.py``). The sampler never
runs on a serving thread — scraping happens on its own daemon thread,
reads registry values under the registry lock only, and touches no
device.

Segment format (version 1): JSONL. Line 1 is the header::

    {"kind": "history.segment", "format": 1, "session": ..., "epoch": ...,
     "interval_s": ...}

Every following line is one point::

    {"t": <epoch seconds>, "r": 0,  "s": {"<name{labels}>": <value>, ...}}
    {"t": <bucket start>,  "r": 10, "s": {"<name>": [min, max, mean, last]}}

Histogram series flatten into ``<name>:count`` / ``<name>:sum`` scalar
series so every stored value is a number. ``r`` is the rollup factor in
sampler intervals (0 = raw). Restart join: segments are named
``seg-<epoch_ms>-<seq>.jsonl`` so a lexicographic sort is chronological
across sessions; :func:`read_segments` joins them (``axon_report
--history`` and ``scripts/axon_dash.py`` are the consumers).
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from ..config import settings
from . import _metrics, _recorder

_LOCK = threading.Lock()
_SAMPLER = None

#: segment format version (bump on incompatible layout changes)
FORMAT = 1
#: truthy spellings of SPARSE_TPU_HISTORY selecting the default root
_TRUTHY = ("1", "true", "yes", "on")
#: default root: results/axon/history next to the repo root
_DEFAULT_ROOT = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "results",
    "axon",
    "history",
)
#: committed-segment size target: the active buffer rotates past this
SEGMENT_MAX_BYTES = 256 * 1024
#: atomic checkpoint cadence: the active segment is re-committed every
#: N points, so a crash loses at most N samples
CHECKPOINT_EVERY = 10
#: in-memory ring depths per resolution (raw keeps ~10 min at 1 s)
RING_DEPTH = {0: 600, 10: 360, 60: 240}
#: rollup factors (in sampler intervals)
ROLLUPS = (10, 60)


def root_from_settings() -> str | None:
    """The history root implied by settings, or ``None`` when off:
    ``SPARSE_TPU_HISTORY`` is either a truthy spelling (default root) or
    itself a directory path; ``SPARSE_TPU_HISTORY_DIR`` wins."""
    v = (settings.history or "").strip()
    if not v:
        return None
    override = (settings.history_dir or "").strip()
    if override:
        return override
    if v.lower() in _TRUTHY:
        return _DEFAULT_ROOT
    return v


def flatten(snap: dict) -> dict:
    """Flatten a :func:`._metrics.snapshot` into all-scalar series:
    histogram entries become ``<key>:count`` / ``<key>:sum``."""
    out = {}
    for k, v in snap.items():
        if isinstance(v, dict):
            out[k + ":count"] = v.get("count", 0)
            out[k + ":sum"] = v.get("sum", 0.0)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = v
    return out


class _Bucket:
    """One open rollup bucket: per-series [min, max, sum, n, last]."""

    __slots__ = ("start", "series")

    def __init__(self, start: float):
        self.start = start
        self.series: dict = {}

    def add(self, flat: dict) -> None:
        for k, v in flat.items():
            s = self.series.get(k)
            if s is None:
                self.series[k] = [v, v, v, 1, v]
            else:
                if v < s[0]:
                    s[0] = v
                if v > s[1]:
                    s[1] = v
                s[2] += v
                s[3] += 1
                s[4] = v

    def point(self, r: int) -> dict:
        return {
            "t": round(self.start, 3),
            "r": r,
            "s": {
                k: [s[0], s[1], round(s[2] / s[3], 9), s[4]]
                for k, s in self.series.items()
            },
        }


class Sampler:
    """The history sampler: scrape thread + rings + segment writer.

    Construct via :func:`start` (the module singleton) or directly in
    tests; ``observe(now, flat)`` is the deterministic test seam the
    thread's ``_sample_once`` also goes through."""

    def __init__(self, root: str, interval_s: float | None = None,
                 cap_mb: int | None = None,
                 segment_max_bytes: int = SEGMENT_MAX_BYTES):
        self.root = str(root)
        self.interval_s = float(
            interval_s if interval_s is not None else settings.history_interval
        )
        self.cap_bytes = int(
            (cap_mb if cap_mb is not None else settings.history_cap_mb)
            * 1024 * 1024
        )
        self.segment_max_bytes = int(segment_max_bytes)
        self.session = _recorder.session_info()["session"]
        self._lock = threading.RLock()
        self._rings: dict = {
            res: _ring_deque(depth) for res, depth in RING_DEPTH.items()
        }
        self._buckets: dict = {}  # rollup factor -> open _Bucket
        # the active segment: header + committed-so-far point lines,
        # re-written atomically every CHECKPOINT_EVERY points
        self._seq = 0
        self._seg_lines: list = []
        self._seg_bytes = 0
        self._seg_path = None
        self._uncheckpointed = 0
        # stats (the /dash + state() surface)
        self.samples = 0
        self.rotations = 0
        self.gc_evicted = 0
        self.write_errors = 0
        self._thread = None
        self._stop = threading.Event()
        self._open_segment()

    # -- segment lifecycle -------------------------------------------------
    def _header(self) -> dict:
        return {
            "kind": "history.segment",
            "format": FORMAT,
            "session": self.session,
            "epoch": round(time.time(), 3),
            "interval_s": self.interval_s,
        }

    def _open_segment(self) -> None:
        self._seq += 1
        stamp = int(time.time() * 1000)
        self._seg_path = os.path.join(
            self.root, f"seg-{stamp:013d}-{self._seq:04d}.jsonl"
        )
        hdr = json.dumps(self._header())
        self._seg_lines = [hdr]
        self._seg_bytes = len(hdr) + 1
        self._uncheckpointed = 0

    def _commit(self) -> None:
        """Atomically (re)write the active segment: tmp + fsync +
        os.replace — a crash mid-commit leaves the previous committed
        content intact, never a torn file."""
        path = self._seg_path
        try:
            os.makedirs(self.root, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write("\n".join(self._seg_lines) + "\n")
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
            self._uncheckpointed = 0
        except OSError:
            self.write_errors += 1

    def _rotate(self) -> None:
        self._commit()
        self.rotations += 1
        self._gc()
        self._open_segment()

    def _gc(self) -> None:
        """Byte-capped retention: delete oldest committed segments past
        the budget (name sort is chronological by construction)."""
        try:
            segs = []
            for f in sorted(os.listdir(self.root)):
                if not (f.startswith("seg-") and f.endswith(".jsonl")):
                    continue
                path = os.path.join(self.root, f)
                try:
                    segs.append((path, os.path.getsize(path)))
                except OSError:
                    pass
            total = sum(sz for _, sz in segs)
            for path, sz in segs:
                if total <= self.cap_bytes:
                    break
                if path == self._seg_path:
                    continue  # never evict the active segment
                try:
                    os.remove(path)
                    total -= sz
                    self.gc_evicted += 1
                except OSError:
                    pass
        except OSError:
            pass

    def _append_point(self, point: dict) -> None:
        line = json.dumps(point)
        self._seg_lines.append(line)
        self._seg_bytes += len(line) + 1
        self._uncheckpointed += 1
        if self._seg_bytes >= self.segment_max_bytes:
            self._rotate()
        elif self._uncheckpointed >= CHECKPOINT_EVERY:
            self._commit()

    # -- sampling ----------------------------------------------------------
    def observe(self, now: float, flat: dict) -> None:
        """Ingest one flattened sample at wall-clock ``now`` — the
        deterministic seam the scrape thread and tests share."""
        with self._lock:
            self.samples += 1
            raw = {"t": round(now, 3), "r": 0, "s": flat}
            self._rings[0].append(raw)
            self._append_point(raw)
            for r in ROLLUPS:
                width = r * self.interval_s
                start = (now // width) * width
                bkt = self._buckets.get(r)
                if bkt is not None and bkt.start != start:
                    pt = bkt.point(r)
                    self._rings[r].append(pt)
                    self._append_point(pt)
                    bkt = None
                if bkt is None:
                    bkt = self._buckets[r] = _Bucket(start)
                bkt.add(flat)

    def _sample_once(self) -> None:
        self.observe(time.time(), flatten(_metrics.snapshot()))

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self._sample_once()
            except Exception:  # noqa: BLE001 - the scrape must survive
                pass

    def start(self) -> "Sampler":
        """Begin scraping on a daemon thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="sparse-tpu-axon-history",
                daemon=True,
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        """Stop the thread, flush open rollup buckets and commit the
        active segment."""
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None
        with self._lock:
            for r in ROLLUPS:
                bkt = self._buckets.pop(r, None)
                if bkt is not None and bkt.series:
                    pt = bkt.point(r)
                    self._rings[r].append(pt)
                    line = json.dumps(pt)
                    self._seg_lines.append(line)
                    self._seg_bytes += len(line) + 1
            self._commit()

    def flush(self) -> None:
        """Commit the active segment now (the flight recorder calls this
        before embedding a window so the disk view is current)."""
        with self._lock:
            self._commit()

    # -- views -------------------------------------------------------------
    def window(self, seconds: float = 300.0, res: int = 0) -> list:
        """Recent in-memory points at resolution ``res`` (a rollup
        factor: 0 raw, 10, 60) covering the last ``seconds``."""
        cutoff = time.time() - float(seconds)
        with self._lock:
            ring = self._rings.get(int(res))
            if ring is None:
                return []
            return [p for p in ring if p["t"] >= cutoff]

    def state(self) -> dict:
        with self._lock:
            return {
                "enabled": True,
                "running": bool(self._thread and self._thread.is_alive()),
                "root": self.root,
                "interval_s": self.interval_s,
                "cap_mb": round(self.cap_bytes / (1024 * 1024), 3),
                "session": self.session,
                "samples": self.samples,
                "rotations": self.rotations,
                "gc_evicted": self.gc_evicted,
                "write_errors": self.write_errors,
                "ring_depths": {
                    str(r): len(ring) for r, ring in self._rings.items()
                },
            }


def _ring_deque(depth: int):
    return collections.deque(maxlen=depth)


# ---------------------------------------------------------------------------
# reading committed segments back (verify-then-load; restart join)
# ---------------------------------------------------------------------------
def _quarantine(root: str, fname: str) -> None:
    """Move an unverifiable segment aside (degrade, don't die) and count
    it on the always-on registry."""
    try:
        qdir = os.path.join(root, "quarantine")
        os.makedirs(qdir, exist_ok=True)
        os.replace(
            os.path.join(root, fname), os.path.join(qdir, fname)
        )
    except OSError:
        pass
    _metrics.counter(
        "history.quarantined",
        help="history segments that failed verify-then-load and were "
        "moved into quarantine/",
    ).inc()


def read_segments(root: str | None = None, res: int | None = None) -> list:
    """Join every committed segment under ``root`` into one time-ordered
    point list (the restart-join read: segments from prior sessions sort
    before the current one by name). Each point gains a ``session``
    field from its segment header.

    Verify-then-load: a segment whose first line is not a format-1
    ``history.segment`` header is quarantined and skipped; an
    undecodable point line ends that segment's read (torn tail — the
    valid prefix is kept). ``res`` filters to one resolution."""
    root = root or root_from_settings() or _DEFAULT_ROOT
    points: list = []
    try:
        segs = sorted(
            f for f in os.listdir(root)
            if f.startswith("seg-") and f.endswith(".jsonl")
        )
    except OSError:
        return points
    for fname in segs:
        try:
            with open(os.path.join(root, fname)) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        try:
            hdr = json.loads(lines[0]) if lines else None
        except (json.JSONDecodeError, ValueError):
            hdr = None
        if (
            not isinstance(hdr, dict)
            or hdr.get("kind") != "history.segment"
            or hdr.get("format") != FORMAT
        ):
            _quarantine(root, fname)
            continue
        session = hdr.get("session")
        for line in lines[1:]:
            if not line.strip():
                continue
            try:
                p = json.loads(line)
            except json.JSONDecodeError:
                _metrics.counter(
                    "history.truncated",
                    help="history segments whose tail was torn; the "
                    "valid prefix was kept",
                ).inc()
                break  # torn tail: keep the prefix, drop the rest
            if not isinstance(p, dict) or "t" not in p:
                break
            if res is not None and p.get("r", 0) != res:
                continue
            p["session"] = session
            points.append(p)
    points.sort(key=lambda p: (p["t"], p.get("r", 0)))
    return points


def segments_state(root: str | None = None) -> dict:
    """On-disk listing for tooling: segment names, sizes, sessions."""
    root = root or root_from_settings() or _DEFAULT_ROOT
    segs = []
    try:
        names = sorted(
            f for f in os.listdir(root)
            if f.startswith("seg-") and f.endswith(".jsonl")
        )
    except OSError:
        names = []
    for f in names:
        path = os.path.join(root, f)
        try:
            segs.append({"name": f, "bytes": os.path.getsize(path)})
        except OSError:
            pass
    return {"root": root, "segments": segs,
            "total_bytes": sum(s["bytes"] for s in segs)}


# ---------------------------------------------------------------------------
# the process singleton
# ---------------------------------------------------------------------------
def enabled() -> bool:
    """True when the settings gate is on (one attribute check — the
    zero-overhead discipline's whole cost on the disabled path)."""
    return bool(settings.history)


def current() -> Sampler | None:
    """The live sampler, or ``None``."""
    return _SAMPLER


def start(root: str | None = None, interval_s: float | None = None,
          cap_mb: int | None = None) -> Sampler:
    """Get-or-create the process sampler and begin scraping. Explicit
    arguments win over settings (tests, bench's overhead probe)."""
    global _SAMPLER
    with _LOCK:
        if _SAMPLER is None:
            _SAMPLER = Sampler(
                root or root_from_settings() or _DEFAULT_ROOT,
                interval_s=interval_s, cap_mb=cap_mb,
            )
        return _SAMPLER.start()


def maybe_start() -> Sampler | None:
    """Start the sampler iff the settings gate is on — the serving
    path's auto-enable hook (``SolveSession.__init__``). One attribute
    check when off."""
    if not settings.history:
        return None
    return start()


def stop() -> None:
    """Stop and drop the process sampler (idempotent); flushes the
    active segment."""
    global _SAMPLER
    with _LOCK:
        smp, _SAMPLER = _SAMPLER, None
    if smp is not None:
        smp.stop()


def state() -> dict:
    """The sampler's diagnostics (the ``/dash`` JSON block), or a
    disabled stub."""
    smp = _SAMPLER
    if smp is None:
        return {"enabled": False, "running": False}
    return smp.state()


def window(seconds: float = 300.0, res: int = 0) -> list:
    """Recent in-memory points from the live sampler (empty when off) —
    what the flight recorder embeds and ``/dash`` renders."""
    smp = _SAMPLER
    if smp is None:
        return []
    return smp.window(seconds=seconds, res=res)
