"""SLO error-budget engine (Axon v7): multi-window burn-rate alerting.

The v5 watchdog's ``slo_miss_rate`` rule is an instantaneous-window
threshold: one bad tick's worth of tickets can page, and a slow leak
that never crosses the per-window threshold never does. This module
replaces it with the SRE error-budget formulation: a serving
**objective** (e.g. "99% of tickets within the session's slo_ms")
allots an error budget (1 - objective); the **burn rate** over a window
is how many times faster than allotted that budget is being consumed::

    burn(W) = miss_rate(W) / (1 - objective)

Stock rules follow the standard multi-window pairing — a rule fires
only when BOTH its short and long window burn past the threshold (the
short window makes it responsive, the long window blip-proof):

* ``slo_fast_burn`` — 5 m & 1 h windows, burn > 14.4 (2% of a 30-day
  budget in one hour), severity ``page``.
* ``slo_slow_burn`` — 6 h & 3 d windows, burn > 1.0 (budget-neutral
  line), severity ``warn``.

Per-tenant evaluation (the v7 watchdog satellite): each rule's value is
the WORST (tenant, window-pair) burn — a single tenant's breach can no
longer hide inside a healthy aggregate. The aggregate rides the
``batch.slo_misses`` / ``batch.ticket_latency`` families; per-tenant
numbers ride the v7 ``usage.tickets{tenant}`` /
``usage.slo_misses{tenant}`` metering counters (``batch/service.py``).

The :class:`Engine` keeps its own bounded sample ring (one cumulative
(miss, total) snapshot per tenant per evaluation) so burn windows work
with or without the history store; windows shorter than the available
ring use the partial window (a fresh process alerts on what it can
see rather than staying blind for 5 minutes). ``budget.burn`` events
(rate-limited per rule+tenant) record breaches into the session log;
``/budget`` on ``telemetry.serve()`` serves :func:`state`.

Zero new overhead on the serving path: the engine only READS registry
values, sampling happens inside watchdog evaluation (or on demand), and
no budget object exists until a rule or ``state()`` asks for one.
"""

from __future__ import annotations

import collections
import threading
import time

from . import _metrics, _recorder
from ._watchdog import Rule

_LOCK = threading.Lock()
_ENGINE = None

#: default serving objective: 99% of tickets inside the SLO
DEFAULT_OBJECTIVE = 0.99
#: stock window geometry (seconds) and thresholds (burn multiples)
FAST_WINDOWS = (300.0, 3600.0)
SLOW_WINDOWS = (21600.0, 259200.0)
FAST_BURN = 14.4
SLOW_BURN = 1.0
#: engine sample ring depth (at 1 Hz evaluation ~ 4.5 h of lookback)
RING_DEPTH = 16384
#: min seconds between budget.burn events per (rule, tenant)
EVENT_INTERVAL_S = 30.0

#: the aggregate pseudo-tenant label
AGGREGATE = ""


def _read_counts() -> dict:
    """Cumulative ``{tenant: (misses, total)}`` from the always-on
    registry. ``""`` is the aggregate over every ticket; named tenants
    come from the v7 usage metering families (only tickets submitted
    with a tenant label appear there)."""
    total = sum(h.count for h in _metrics.family("batch.ticket_latency"))
    miss = _metrics.counter("batch.slo_misses").value
    counts = {AGGREGATE: [float(miss), float(total)]}
    for m in _metrics.family("usage.tickets"):
        tenant = m.labels.get("tenant")
        if not tenant or tenant == "-":
            continue
        c = counts.setdefault(tenant, [0.0, 0.0])
        c[1] += float(m.value)
    for m in _metrics.family("usage.slo_misses"):
        tenant = m.labels.get("tenant")
        if not tenant or tenant == "-":
            continue
        c = counts.setdefault(tenant, [0.0, 0.0])
        c[0] += float(m.value)
    return {t: (c[0], c[1]) for t, c in counts.items()}


class Engine:
    """Windowed burn-rate math over a bounded ring of cumulative
    samples. ``sample(now)`` appends one reading; ``burn(window_s,
    now)`` returns ``{tenant: burn}`` for every tenant with traffic in
    the window. ``now`` and the count reader are injectable (tests
    drive hand-computed fixtures through both)."""

    def __init__(self, objective: float = DEFAULT_OBJECTIVE,
                 read_counts=_read_counts, ring_depth: int = RING_DEPTH):
        if not 0.0 < objective < 1.0:
            raise ValueError(f"objective must be in (0, 1), got {objective}")
        self.objective = float(objective)
        self.budget_rate = 1.0 - self.objective
        self._read = read_counts
        self._ring: collections.deque = collections.deque(maxlen=ring_depth)
        self._lock = threading.Lock()
        self._last_event: dict = {}

    def sample(self, now: float | None = None) -> None:
        """Append one cumulative snapshot (at most one per distinct
        ``now`` — rules sharing the engine in one tick don't double-
        sample)."""
        now = time.monotonic() if now is None else float(now)
        counts = self._read()
        with self._lock:
            if self._ring and self._ring[-1][0] >= now:
                return
            self._ring.append((now, counts))

    def burn(self, window_s: float, now: float | None = None) -> dict:
        """Per-tenant burn rate over the trailing ``window_s``: the
        miss-rate delta between now's sample and the oldest sample
        inside the window (or the ring's oldest — partial windows are
        legal), divided by the budget rate. Tenants whose ticket count
        didn't move in the window are omitted (idle ≠ healthy ≠
        burning)."""
        now = time.monotonic() if now is None else float(now)
        with self._lock:
            if len(self._ring) < 2:
                return {}
            newest = self._ring[-1]
            cutoff = now - float(window_s)
            base = self._ring[0]
            for s in self._ring:
                if s[0] >= cutoff:
                    break
                base = s
        out = {}
        for tenant, (m1, t1) in newest[1].items():
            m0, t0 = base[1].get(tenant, (0.0, 0.0))
            dt = t1 - t0
            if dt <= 0:
                continue
            out[tenant] = ((m1 - m0) / dt) / self.budget_rate
        return out

    def worst_burn(self, windows, now: float | None = None):
        """The multi-window reading: per tenant, the MIN burn across the
        window pair (both must breach for the pair to read high);
        returns ``(burn, tenant)`` for the worst tenant, or ``(None,
        None)`` when no tenant had traffic in every window."""
        now = time.monotonic() if now is None else float(now)
        per: dict = {}
        for w in windows:
            for tenant, b in self.burn(w, now=now).items():
                per.setdefault(tenant, []).append(b)
        worst, who = None, None
        nwin = len(tuple(windows))
        for tenant, bs in per.items():
            if len(bs) < nwin:
                continue
            b = min(bs)
            if worst is None or b > worst:
                worst, who = b, tenant
        return worst, who

    def report(self, now: float | None = None) -> dict:
        """The ``/budget`` payload body: per-window per-tenant burns
        plus budget-remaining arithmetic over the ring's span."""
        now = time.monotonic() if now is None else float(now)
        self.sample(now)
        windows = {}
        for w in sorted(set(FAST_WINDOWS + SLOW_WINDOWS)):
            windows[str(int(w))] = {
                t: round(b, 4) for t, b in self.burn(w, now=now).items()
            }
        with self._lock:
            span = (
                self._ring[-1][0] - self._ring[0][0]
                if len(self._ring) > 1 else 0.0
            )
            counts = dict(self._ring[-1][1]) if self._ring else {}
        tenants = {}
        for t, (m, n) in counts.items():
            allowed = n * self.budget_rate
            tenants[t or "aggregate"] = {
                "tickets": int(n),
                "slo_misses": int(m),
                "budget_allowed": round(allowed, 3),
                "budget_remaining": round(allowed - m, 3),
            }
        return {
            "objective": self.objective,
            "budget_rate": round(self.budget_rate, 6),
            "span_s": round(span, 3),
            "samples": len(self._ring),
            "burn": windows,
            "tenants": tenants,
        }

    def total_tickets(self) -> float:
        """Aggregate cumulative ticket count at the newest sample."""
        with self._lock:
            if not self._ring:
                return 0.0
            return self._ring[-1][1].get(AGGREGATE, (0.0, 0.0))[1]

    def _maybe_event(self, rule: str, tenant, burn: float,
                    windows) -> None:
        """Rate-limited ``budget.burn`` breadcrumb into the session log
        (telemetry on): WHEN the budget started burning, per tenant."""
        key = (rule, tenant)
        now = time.monotonic()
        last = self._last_event.get(key)
        if last is not None and now - last < EVENT_INTERVAL_S:
            return
        self._last_event[key] = now
        _recorder.record(
            "budget.burn", rule=rule, tenant=tenant or "aggregate",
            burn=round(burn, 4), windows=[int(w) for w in windows],
            objective=self.objective,
        )


# ---------------------------------------------------------------------------
# the stock burn-rate rules (what default_rules() installs)
# ---------------------------------------------------------------------------
def burn_rule(name: str, windows, trigger: float, *,
              clear: float | None = None, severity: str = "warn",
              engine: Engine | None = None, min_tickets: int = 1,
              **kw) -> Rule:
    """A multi-window burn-rate :class:`Rule`: fires when the worst
    (tenant, window-pair) burn exceeds ``trigger`` in EVERY window of
    ``windows``. ``engine`` defaults to the process engine; explicit
    ``windows`` let chaos drills compress 5m/1h geometry into
    seconds."""
    windows = tuple(float(w) for w in windows)
    clear = trigger / 2.0 if clear is None else float(clear)

    def value():
        eng = engine if engine is not None else _engine()
        eng.sample()
        if eng.total_tickets() < min_tickets:
            return None
        burn, tenant = eng.worst_burn(windows)
        if burn is None:
            return None
        if burn > trigger:
            eng._maybe_event(name, tenant, burn, windows)
        return burn

    return Rule(name, value, trigger, clear=clear, op=">",
                severity=severity, **kw)


def fast_burn_rule(windows=FAST_WINDOWS, trigger: float = FAST_BURN,
                   severity: str = "page", **kw) -> Rule:
    """The paging rule: short/long = 5 m / 1 h, burn > 14.4 — a fast
    leak that would exhaust a 30-day budget's 2% within the hour."""
    return burn_rule("slo_fast_burn", windows, trigger,
                     severity=severity, **kw)


def slow_burn_rule(windows=SLOW_WINDOWS, trigger: float = SLOW_BURN,
                   severity: str = "warn", **kw) -> Rule:
    """The ticket-queue rule: short/long = 6 h / 3 d, burn > 1.0 — the
    budget is being consumed faster than allotted, sustained."""
    return burn_rule("slo_slow_burn", windows, trigger,
                     severity=severity, **kw)


def default_rules(engine: Engine | None = None) -> list:
    """The stock budget rule pair (what ``_watchdog.default_rules``
    installs in place of the v5 instantaneous ``slo_miss_rate``)."""
    return [
        fast_burn_rule(engine=engine),
        slow_burn_rule(engine=engine),
    ]


# ---------------------------------------------------------------------------
# usage metering readback (batch/service.py writes the families)
# ---------------------------------------------------------------------------
def usage_stats() -> dict:
    """Per-tenant rollup of the always-on ``usage.*`` families — the
    ``session_stats()['usage']`` block and part of ``/budget``. Empty
    dict when nothing was metered (pre-v7 consumers see no new key)."""
    tenants: dict = {}

    def _acc(metric_name, field, count_attr="value"):
        for m in _metrics.family(metric_name):
            tenant = m.labels.get("tenant", "-") or "-"
            row = tenants.setdefault(tenant, {})
            row[field] = row.get(field, 0) + getattr(m, count_attr)

    _acc("usage.tickets", "tickets")
    _acc("usage.slo_misses", "slo_misses")
    _acc("usage.lanes", "lanes")
    _acc("usage.device_ms", "device_ms")
    _acc("usage.collective_bytes", "collective_bytes")
    _acc("usage.ingest", "ingest")
    for row in tenants.values():
        if "device_ms" in row:
            row["device_ms"] = round(row["device_ms"], 3)
    return tenants


# ---------------------------------------------------------------------------
# the process singleton (what /budget serves)
# ---------------------------------------------------------------------------
def _engine() -> Engine:
    global _ENGINE
    with _LOCK:
        if _ENGINE is None:
            _ENGINE = Engine()
        return _ENGINE


def engine(objective: float | None = None) -> Engine:
    """Get-or-create the process budget engine. An existing engine is
    returned as-is; pass ``objective`` before first use to change it."""
    global _ENGINE
    with _LOCK:
        if _ENGINE is None:
            _ENGINE = Engine(
                objective=DEFAULT_OBJECTIVE if objective is None
                else objective
            )
        return _ENGINE


def reset() -> None:
    """Drop the process engine (tests; a fresh engine re-reads the
    registry from its current cumulative values)."""
    global _ENGINE
    with _LOCK:
        _ENGINE = None


def state() -> dict:
    """The ``/budget`` payload: engine report + usage metering rollup
    (a disabled-shaped stub when no engine has ever been touched —
    reading must not allocate one on a box that never served)."""
    eng = _ENGINE
    usage = usage_stats()
    if eng is None:
        return {"enabled": False, "usage": usage}
    out = {"enabled": True, "usage": usage}
    out.update(eng.report())
    return out
