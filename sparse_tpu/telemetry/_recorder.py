"""Event recorder core: bounded ring buffer + JSONL sink + counters.

Reference analog: Legion's profiling/mapping introspection gives the
reference stack per-task timing and communication attribution for free
(SURVEY §5); JAX/XLA has nothing equivalent at the library level, so this
module is the substrate every instrumentation site reports through.

Design rules (the whole point of the module):

* **Zero overhead when disabled.** Every entry point's first statement is
  one attribute check on ``settings.telemetry``; nothing allocates, locks,
  or touches the filesystem on the disabled path.
* **Fault-tolerant sink.** The JSONL sink shares
  ``results/axon/records.jsonl`` with bench.py's hardware-evidence
  records (telemetry events carry ``kind`` and no top-level ``metric``,
  so bench's freshest-TPU-record scan never confuses the two). Any
  filesystem failure warns once and drops the sink — the in-memory ring
  keeps working.
* **Host-side only.** ``record()`` must be called with concrete values;
  traced code reaches it through ``jax.debug.callback`` taps (see
  ``linalg._cg_device_loop``) or not at all.
"""

from __future__ import annotations

import collections
import json
import os
import threading
import time

from ..config import settings
from . import _context, _metrics

_LOCK = threading.RLock()
_RING: collections.deque | None = None
# count()/add_bytes() live on the always-on metrics registry (one metrics
# surface — telemetry/_metrics.py); these are the family names there.
_COUNTS_METRIC = "telemetry.counts"
_BYTES_METRIC = "telemetry.bytes"
_DROPPED = 0  # events evicted from a full ring (satellite: overflow was silent)
_SPANS: dict[str, list] = {}
_SINK = None  # lazily-opened append-mode file object
_SINK_FAILED = False
_SINK_PATH_OPEN: str | None = None
_PATH_OVERRIDE: str | None = None
# -- per-process identity + session clock base (Axon v4) --------------------
_IDENT: dict | None = None  # cached process_identity()
_SESSION: dict | None = None  # {"epoch", "mono", "session"} clock base
_SESSION_STAMPED: set = set()  # sink paths that already carry session.start

# repo root = two levels up from this package (sparse_tpu/telemetry/)
_DEFAULT_SINK = os.path.join(
    os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__)))),
    "results",
    "axon",
    "records.jsonl",
)


def enabled() -> bool:
    """True when the telemetry subsystem records (``settings.telemetry`` /
    ``SPARSE_TPU_TELEMETRY``). Instrumentation sites gate on this — one
    attribute read — so the disabled path stays measurement-free."""
    return bool(settings.telemetry)


def _env_int(name: str):
    v = os.environ.get(name)
    if v is None:
        return None
    try:
        return int(v)
    except ValueError:
        return None


def process_identity() -> dict:
    """This controller process's identity, cached for the process
    lifetime: ``{"pi": process_index, "pid", "procs": process_count,
    "devices", "backend"}``.

    ``SPARSE_TPU_PROCESS_INDEX`` / ``SPARSE_TPU_PROCESS_COUNT`` override
    the jax runtime answer (tests simulate multi-controller without N
    hosts; fleet launchers can stamp identity before jax initializes).
    Every failure degrades to the single-controller identity — the
    recorder must never raise from an identity probe."""
    global _IDENT
    if _IDENT is not None:
        return _IDENT
    pi = _env_int("SPARSE_TPU_PROCESS_INDEX")
    procs = _env_int("SPARSE_TPU_PROCESS_COUNT")
    devices = None
    backend = ""
    try:
        import jax

        if pi is None:
            pi = int(jax.process_index())
        if procs is None:
            procs = int(jax.process_count())
        devices = len(jax.devices())
        backend = str(jax.default_backend())
    except Exception:
        pass
    _IDENT = {
        "pi": int(pi or 0),
        "pid": os.getpid(),
        "procs": int(procs or 1),
        "devices": devices,
        "backend": backend,
    }
    return _IDENT


def reset_identity() -> None:
    """Drop the cached identity (tests that monkeypatch the env
    overrides; a fork that wants its own pid stamp)."""
    global _IDENT, _SESSION
    with _LOCK:
        _IDENT = None
        _SESSION = None
        _SESSION_STAMPED.clear()


def session_info() -> dict:
    """The session clock base: ``{"epoch": wall-clock start, "mono":
    monotonic reading at that instant, "session": id}``. Established at
    the first read and stable for the process lifetime — the pair is what
    lets ``scripts/axon_merge.py`` clock-align per-process logs (aligned
    ts = epoch + per-event monotonic offset ``tm``)."""
    global _SESSION
    if _SESSION is None:
        with _LOCK:
            if _SESSION is None:
                ep = time.time()
                _SESSION = {
                    "epoch": ep,
                    "mono": time.monotonic(),
                    "session": f"{os.getpid():x}-{int(ep)}",
                }
    return _SESSION


def _session_start_event() -> dict:
    base = session_info()
    ident = process_identity()
    return {
        "kind": "session.start",
        "ts": base["epoch"],
        "tm": 0.0,
        "epoch": base["epoch"],
        "mono": base["mono"],
        "pi": ident["pi"],
        "pid": ident["pid"],
        "procs": ident["procs"],
        "devices": ident["devices"],
        "backend": ident["backend"],
        "session": base["session"],
    }


def sink_path() -> str:
    """Resolved JSONL sink path (override > settings > default).

    Under multi-controller (``process_count > 1`` — or the env overrides
    simulating it) the sink splits per process: ``records.jsonl`` becomes
    ``records.<pid>.jsonl``, so N controllers on shared storage never
    interleave writes into one file; ``scripts/axon_merge.py`` recombines
    them into one session log."""
    base = _PATH_OVERRIDE or settings.telemetry_path or _DEFAULT_SINK
    try:
        ident = process_identity()
        if ident["procs"] > 1:
            root, ext = os.path.splitext(base)
            return f"{root}.{ident['pid']}{ext or '.jsonl'}"
    except Exception:
        pass
    return base


def configure(path: str | None = None) -> None:
    """Point the JSONL sink somewhere else (tests, bench subprocesses).

    ``None`` restores the settings/default resolution. Closes any open
    sink so the next record reopens at the new path; also clears the
    failed-sink latch so a previously unwritable location can be retried.
    """
    global _PATH_OVERRIDE, _SINK, _SINK_FAILED, _SINK_PATH_OPEN
    with _LOCK:
        _PATH_OVERRIDE = path
        if _SINK is not None:
            try:
                _SINK.close()
            except OSError:
                pass
        _SINK = None
        _SINK_PATH_OPEN = None
        _SINK_FAILED = False


def _ring() -> collections.deque:
    global _RING
    if _RING is None or _RING.maxlen != settings.telemetry_ring:
        old = list(_RING) if _RING is not None else []
        _RING = collections.deque(old, maxlen=settings.telemetry_ring)
    return _RING


def _jsonable(v):
    """Best-effort JSON coercion for numpy/jax scalars and odd values —
    the sink must never raise back into a hot path."""
    try:
        import numpy as np

        if isinstance(v, (np.integer,)):
            return int(v)
        if isinstance(v, (np.floating,)):
            return float(v)
        if isinstance(v, np.ndarray):
            return v.tolist()
    except Exception:
        pass
    return str(v)


def _write(ev: dict) -> None:
    """Append one event line to the sink; failures disable the sink."""
    global _SINK, _SINK_FAILED, _SINK_PATH_OPEN
    if _SINK_FAILED:
        return
    path = sink_path()
    try:
        if _SINK is None or _SINK_PATH_OPEN != path:
            if _SINK is not None:
                _SINK.close()
            os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
            _SINK = open(path, "a")
            _SINK_PATH_OPEN = path
        if path not in _SESSION_STAMPED:
            # first write to this sink: lead with the session-start record
            # (process identity + the epoch/monotonic clock base
            # axon_merge aligns on). Sink-only by design — the in-memory
            # ring stays event-count-faithful for summaries.
            _SESSION_STAMPED.add(path)
            if ev.get("kind") != "session.start":
                _SINK.write(
                    json.dumps(_session_start_event(), default=_jsonable)
                    + "\n"
                )
        _SINK.write(json.dumps(ev, default=_jsonable) + "\n")
        _SINK.flush()
    except (OSError, ValueError):
        _SINK_FAILED = True
        _SINK = None
        from ..utils import user_warning

        user_warning(
            f"telemetry: JSONL sink {path!r} unwritable; events stay "
            "in the in-memory ring only"
        )


def record(kind: str, **fields):
    """Record one structured event: ``record("solver.iter", iter=3, ...)``.

    No-op (one attribute check) when telemetry is disabled. Events get
    ``kind`` and a ``ts`` wall-clock stamp; inside an active
    :func:`~._context.ticket_scope` they additionally gain a ``tickets``
    field (explicit ``ticket``/``tickets`` fields win) so deep
    instrumentation sites attribute to the requests they serve. A
    numeric ``bytes`` field additionally accumulates into the per-kind
    byte totals reported by :func:`~sparse_tpu.telemetry.summary`.
    Returns the event dict, or ``None`` when disabled.
    """
    if not settings.telemetry:
        return None
    global _DROPPED
    base = session_info()
    ident = process_identity()
    ev = {
        "kind": kind,
        "ts": time.time(),
        # monotonic offset since session start: the wall-jump-proof
        # timestamp axon_merge aligns multi-host logs on
        "tm": round(time.monotonic() - base["mono"], 6),
        "pi": ident["pi"],
        "pid": ident["pid"],
    }
    ev.update(fields)
    _context.annotate(ev)
    with _LOCK:
        ring = _ring()
        if ring.maxlen is not None and len(ring) == ring.maxlen:
            _DROPPED += 1  # the deque evicts silently; we don't
        ring.append(ev)
        b = fields.get("bytes")
        if isinstance(b, (int, float)) and not isinstance(b, bool):
            _metrics.counter(_BYTES_METRIC, kind=kind).add(int(b))
        _write(ev)
    return ev


def count(name: str, n: int = 1) -> None:
    """Bump an in-memory counter (no event, no I/O) — the cheap form for
    hot-path call counting (kernel dispatches, host syncs, public-API
    provenance scopes). Stored on the always-on metrics registry
    (``telemetry.counts`` family — visible in ``metrics_text()``) and
    surfaced by ``summary()["counts"]``."""
    if not settings.telemetry:
        return
    _metrics.counter(_COUNTS_METRIC, name=name).inc(n)


def add_bytes(kind: str, n) -> None:
    """Accumulate structural comm volume without emitting an event — the
    per-SpMV counter form (an event per eager SpMV would flood the ring).
    Totals appear in ``summary()["bytes_by_kind"]`` and as the
    ``telemetry.bytes`` metrics family."""
    if not settings.telemetry:
        return
    _metrics.counter(_BYTES_METRIC, kind=kind).add(int(n))


def add_span(name: str, dur_s: float) -> None:
    """Feed one span duration into the latency aggregates (p50/p95)."""
    if not settings.telemetry:
        return
    with _LOCK:
        _SPANS.setdefault(name, []).append(float(dur_s))


def events(kind: str | None = None) -> list:
    """Snapshot of the in-memory ring (optionally filtered by kind)."""
    with _LOCK:
        evs = list(_RING or ())
    if kind is not None:
        evs = [e for e in evs if e.get("kind") == kind]
    return evs


def counters() -> dict:
    return {
        k: int(v)
        for k, v in _metrics.label_values(_COUNTS_METRIC, "name").items()
    }


def bytes_by_kind() -> dict:
    return {
        k: int(v)
        for k, v in _metrics.label_values(_BYTES_METRIC, "kind").items()
    }


def dropped() -> int:
    """Events silently evicted from the full ring since the last reset
    (they are still in the JSONL sink when one is writable)."""
    with _LOCK:
        return _DROPPED


def span_durations() -> dict:
    with _LOCK:
        return {k: list(v) for k, v in _SPANS.items()}


def flush() -> None:
    """Flush the JSONL sink (records already flush per line; this exists
    for symmetry and for callers that swap ``configure`` targets)."""
    with _LOCK:
        if _SINK is not None:
            try:
                _SINK.flush()
            except OSError:
                pass


def reset() -> None:
    """Clear the ring, counters, byte totals, drop count and span
    aggregates (the sink file is untouched — it is an append-only
    session log)."""
    global _RING, _DROPPED
    with _LOCK:
        _RING = None
        _DROPPED = 0
        _metrics.remove(_COUNTS_METRIC)
        _metrics.remove(_BYTES_METRIC)
        _SPANS.clear()
