"""Live serving exporter: a stdlib HTTP surface over the telemetry state.

``telemetry.serve()`` starts a daemon-threaded ``http.server`` (off by
default — nothing listens unless called) exposing the three surfaces a
serving operator scrapes:

* ``/metrics`` — the always-on registry as Prometheus text exposition
  (format 0.0.4): plan-cache counters, batch-service levels, per-ticket
  latency histograms, per-program compile/flops gauges.
* ``/healthz`` — liveness + degradation as JSON: the health monitor's
  most recent solve anomalies, kernel-failover latch states, fault
  injection status, uptime. ``status`` is ``"ok"`` unless a failover is
  latched or the last solve flagged anomalies (``"degraded"`` — still
  HTTP 200: degraded is an operating state, not an outage).
* ``/session`` — the live serving picture as JSON: queue depth, bucket
  occupancy, per-session ticket states (``batch.SolveSession``'s weak
  registry), and the compiled-program attribution table.
* ``/alerts`` — the SLO watchdog's rule states (:mod:`._watchdog`):
  per-rule state/value/thresholds, the currently-firing set, tick
  count. A disabled stub when no watchdog exists; the active set is
  also summarized on ``/healthz``.
* ``/incidents`` — the flight recorder's state (:mod:`._flight`):
  capture/suppression counts plus the on-disk bundle listing
  (``scripts/axon_doctor.py`` analyzes a bundle). A disabled stub
  (which still lists pre-existing bundles) when capture is off.
* ``/budget`` — the SLO error-budget engine's state (:mod:`._budget`,
  Axon v7): per-window per-tenant burn rates, budget-remaining
  arithmetic and the per-tenant usage metering rollup.
* ``/dash`` — a self-refreshing HTML sparkline board over the history
  sampler's in-memory rings (:mod:`._history`, Axon v7); a disabled
  stub when no sampler is live. ``scripts/axon_dash.py`` is the
  terminal rendering of the same data from on-disk segments.
* ``/debug/capture`` — ISSUE 12: trigger an on-demand postmortem bundle
  including a short ``jax.profiler`` trace window (:mod:`._profiler`);
  responds with the bundle name (or the rate-limit refusal). The only
  endpoint with a side effect — it writes under the incidents root.

Port robustness (ISSUE 11 satellite): the listener binds with
``SO_REUSEADDR`` and, when the requested port is already taken (the CI
rerun race), falls back to an ephemeral port instead of raising —
``AxonServer.port`` is always the port actually bound, and
``scripts/axon_serve.py`` prints it.

Bounded overhead by construction: every handler reads in-memory state
under the registry locks (no device touch, no event emission, no
filesystem), responses are built per request, and the server thread is
a daemon so it never blocks interpreter exit. ``scripts/axon_serve.py``
is the CLI over this module.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import _budget, _flight, _health, _history, _metrics, _recorder, _watchdog

_LOCK = threading.Lock()
_SERVER = None

#: Prometheus text exposition content type (format 0.0.4)
METRICS_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _process_block() -> dict:
    """Process identity for fleet scrapes (Axon v4 satellite): with N
    controllers each serving its own exporter, a scraper must be able to
    tell WHICH process (and which per-process record file) it reached."""
    try:
        ident = dict(_recorder.process_identity())
        base = _recorder.session_info()
        ident["session"] = base["session"]
        ident["session_epoch"] = base["epoch"]
        ident["sink"] = _recorder.sink_path()
        return ident
    except Exception:
        return {}


def _register_identity_metrics() -> None:
    """Expose identity on the always-on registry so every /metrics scrape
    carries it as labels (the Prometheus *_info convention)."""
    try:
        ident = _recorder.process_identity()
        _metrics.gauge(
            "process.info",
            help="process identity (value is always 1; the labels carry it)",
            process_index=ident["pi"],
            pid=ident["pid"],
            procs=ident["procs"],
            backend=ident["backend"] or "?",
        ).set(1)
        _metrics.gauge(
            "process.devices", help="jax-visible device count"
        ).set(ident["devices"] or 0)
        _metrics.gauge(
            "process.session_epoch",
            help="wall-clock epoch of this process's telemetry session",
        ).set(_recorder.session_info()["epoch"])
    except Exception:
        pass  # identity is best-effort; the exporter must still serve


def _healthz() -> dict:
    """The /healthz payload (also importable for tests/CLIs)."""
    anomalies: list = []
    rep = _health.last_solve_report()
    if rep:
        anomalies = list(rep.get("anomalies") or ())
    latches: dict = {}
    faults_status = {"active": False, "spec": "", "fires": {}}
    try:
        from ..resilience import failover, faults

        latches = failover.latches()
        from ..config import settings

        faults_status = {
            "active": bool(faults.ACTIVE),
            "spec": settings.faults,
            "fires": faults.stats(),
        }
    except Exception:
        pass  # health must answer even mid-teardown
    wd = _watchdog.state()
    active_alerts = list(wd.get("active") or ())
    # the elastic-mesh block (ISSUE 20): per-session serving topology,
    # remesh budgets and flap-guard latches, read from the live
    # FleetPolicy objects — so a shrink that already re-planned shows
    # the NEW fingerprint here, never the ghost one
    mesh: dict = {"sessions": [], "latched": 0}
    try:
        from ..batch import service as _svc

        for s in _svc.sessions_stats():
            row = {"mesh": s.get("mesh")}
            if "elastic" in s:
                row["elastic"] = s["elastic"]
                if s["elastic"].get("latched"):
                    mesh["latched"] += 1
            mesh["sessions"].append(row)
    except Exception:
        pass  # health must answer even with no batch subsystem
    mesh["remeshes"] = {
        m.labels.get("outcome", "?"): m.value
        for m in _metrics.family("fleet.remeshes")
    }
    degraded = (
        bool(latches) or bool(anomalies) or bool(active_alerts)
        or bool(mesh["latched"])
    )
    fl = _flight.current()
    return {
        "status": "degraded" if degraded else "ok",
        "uptime_s": round(time.monotonic() - (_SERVER.t0 if _SERVER else 0), 3)
        if _SERVER else 0.0,
        "process": _process_block(),
        "last_solve_anomalies": anomalies,
        "failover_latches": latches,
        "faults": faults_status,
        "mesh": mesh,
        # failed best-effort device syncs (ISSUE 12 satellite): nonzero
        # means a backend errored inside block_until_ready and the
        # error was swallowed — silent degradation made visible
        "span_sync_errors": _metrics.counter(
            "telemetry.span_sync_errors"
        ).value,
        # the watchdog's firing set (ISSUE 11): /alerts has the detail
        "alerts": {
            "enabled": bool(wd.get("enabled")),
            "active": active_alerts,
            "count": len(active_alerts),
        },
        # the flight recorder's headline (ISSUE 12): /incidents has the
        # bundle listing
        "incidents": {
            "enabled": fl is not None,
            "captures": fl.captures if fl else 0,
            "suppressed": fl.suppressed if fl else 0,
        },
    }


def _session() -> dict:
    """The /session payload: live queue/bucket/ticket state plus the
    program attribution table."""
    from . import _cost

    sessions: list = []
    try:
        from ..batch import service

        sessions = service.sessions_stats()
    except Exception:
        pass  # no batch subsystem imported yet — an empty serving picture
    occupancy = _metrics.histogram("batch.bucket_occupancy")
    return {
        "queue_depth": _metrics.gauge("batch.queue_depth").value,
        "dispatches": _metrics.counter("batch.dispatches").value,
        "bucket_occupancy": {
            "count": occupancy.count,
            "sum": round(occupancy.sum, 6),
        },
        "slo_misses": _metrics.counter("batch.slo_misses").value,
        "sessions": sessions,
        "programs": _cost.programs(),
        "cold_start_s": round(_cost.total_compile_s(), 6),
    }


_SPARK = "▁▂▃▄▅▆▇█"
#: the /dash headline series (substring match against flattened keys)
_DASH_SERIES = (
    "batch.ticket_latency",
    "batch.slo_misses",
    "batch.queue_depth",
    "batch.dispatches",
    "usage.",
)


def _sparkline(values: list) -> str:
    """Unicode block sparkline of a numeric series (shared shape with
    scripts/axon_dash.py's renderer)."""
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return _SPARK[0] * len(vals)
    return "".join(
        _SPARK[min(int((v - lo) / span * (len(_SPARK) - 1) + 0.5),
                   len(_SPARK) - 1)]
        for v in vals
    )


def _dash_html() -> str:
    """The /dash page (Axon v7): a self-refreshing stdlib-rendered
    sparkline board over the history sampler's in-memory raw ring. A
    disabled stub when no sampler is live — the page itself never
    starts one."""
    st = _history.state()
    head = (
        "<!doctype html><html><head><meta charset='utf-8'>"
        "<meta http-equiv='refresh' content='2'>"
        "<title>axon dash</title><style>body{font-family:monospace;"
        "background:#111;color:#ddd;padding:1em}td{padding:0 .6em}"
        ".spark{color:#6cf}</style></head><body><h3>axon /dash</h3>"
    )
    if not st.get("enabled"):
        return (
            head + "<p>history sampler off — set SPARSE_TPU_HISTORY "
            "(or telemetry._history.start()) to enable.</p></body></html>"
        )
    points = _history.window(seconds=300.0, res=0)
    rows = []
    if points:
        keys = sorted(points[-1].get("s", {}))
        shown = [
            k for k in keys if any(s in k for s in _DASH_SERIES)
        ] or keys[:24]
        for k in shown[:40]:
            series = [p["s"].get(k) for p in points if k in p.get("s", {})]
            if not series:
                continue
            rows.append(
                f"<tr><td>{k}</td>"
                f"<td class='spark'>{_sparkline(series[-60:])}</td>"
                f"<td>{series[-1]}</td></tr>"
            )
    body = (
        f"<p>session {st.get('session')} · {st.get('samples')} samples · "
        f"{len(points)} pts in window · root {st.get('root')}</p>"
        "<table><tr><th>series</th><th>last 5 min</th><th>now</th></tr>"
        + "".join(rows) + "</table></body></html>"
    )
    return head + body


class _Handler(BaseHTTPRequestHandler):
    # the exporter is a metrics surface, not an access log
    def log_message(self, fmt, *args):  # noqa: A003 - stdlib signature
        pass

    def _send(self, code: int, body: bytes, ctype: str) -> None:
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _send_json(self, payload: dict, code: int = 200) -> None:
        self._send(
            code, (json.dumps(payload, default=str) + "\n").encode(),
            "application/json; charset=utf-8",
        )

    def do_GET(self):  # noqa: N802 - stdlib signature
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            if path == "/metrics":
                self._send(
                    200, _metrics.metrics_text().encode(),
                    METRICS_CONTENT_TYPE,
                )
            elif path == "/healthz":
                self._send_json(_healthz())
            elif path == "/session":
                self._send_json(_session())
            elif path == "/alerts":
                self._send_json(_watchdog.state())
            elif path == "/incidents":
                self._send_json(_flight.state())
            elif path == "/budget":
                self._send_json(_budget.state())
            elif path == "/dash":
                self._send(
                    200, _dash_html().encode(),
                    "text/html; charset=utf-8",
                )
            elif path == "/debug/capture":
                bundle = _flight.capture_now(reason="manual")
                if bundle is None:
                    # rate-limited (or unwritable root): say so rather
                    # than silently returning an empty success
                    self._send_json(
                        {"ok": False, "reason": "rate-limited"}, 429
                    )
                else:
                    self._send_json({
                        "ok": True,
                        "bundle": os.path.basename(bundle),
                        "dir": bundle,
                    })
            elif path == "/":
                self._send(
                    200,
                    b"sparse_tpu axon exporter: "
                    b"/metrics /healthz /session /alerts /incidents "
                    b"/budget /dash /debug/capture\n",
                    "text/plain; charset=utf-8",
                )
            else:
                self._send_json({"error": f"no such endpoint {path}"}, 404)
        except BrokenPipeError:
            pass  # scraper hung up mid-response
        except Exception as e:  # noqa: BLE001 - exporter never crashes
            try:
                self._send_json({"error": repr(e)}, 500)
            except Exception:
                pass


class _Server(ThreadingHTTPServer):
    # SO_REUSEADDR, explicitly: CI reruns rebind the same port while the
    # previous listener's socket lingers in TIME_WAIT (ISSUE 11 satellite)
    allow_reuse_address = True
    daemon_threads = True


class AxonServer:
    """Handle for a running exporter; ``stop()`` (or context-manager
    exit) shuts the listener down and joins the daemon thread.

    ``port`` is always the port actually bound; when the requested port
    was taken the listener fell back to an ephemeral one and
    ``fallback`` is True (``requested_port`` keeps the ask)."""

    def __init__(self, host: str, port: int):
        self.requested_port = int(port)
        try:
            self._httpd = _Server((host, port), _Handler)
        except OSError:
            if not port:
                raise  # an ephemeral bind failing is a real error
            # port in use (a parallel test run, a lingering exporter):
            # serve on an ephemeral port instead of raising — the caller
            # reads the real port back from the handle
            self._httpd = _Server((host, 0), _Handler)
        self.host = host
        self.port = int(self._httpd.server_address[1])
        self.fallback = bool(port) and self.port != self.requested_port
        self.t0 = time.monotonic()
        self._thread = threading.Thread(
            target=self._httpd.serve_forever,
            name="sparse-tpu-axon-serve",
            daemon=True,
        )
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def stop(self) -> None:
        global _SERVER
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=5)
        with _LOCK:
            if _SERVER is self:
                _SERVER = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
        return False


def serve(port: int = 0, host: str = "127.0.0.1") -> AxonServer:
    """Start (or return the already-running) exporter. ``port=0`` binds
    an ephemeral port — read it back from the handle (``server.port``).
    The server is a daemon thread: it never outlives the process and
    costs nothing until a scraper connects."""
    global _SERVER
    with _LOCK:
        if _SERVER is not None:
            return _SERVER
        _register_identity_metrics()
        _SERVER = AxonServer(host, port)
        return _SERVER


def serving() -> AxonServer | None:
    """The live exporter handle, or ``None`` when not serving."""
    return _SERVER


def stop_serving() -> None:
    """Stop the exporter if one is running (idempotent)."""
    s = _SERVER
    if s is not None:
        s.stop()
