"""Aggregation API: one dict summarizing the session's telemetry.

The shape bench.py embeds into its session record — counts, per-kind
event totals, span latency percentiles, and structural bytes moved per
collective family. Pure host arithmetic over the recorder's in-memory
state; never touches a device.
"""

from __future__ import annotations

from . import _recorder


def _percentile(sorted_vals: list, q: float) -> float:
    """Nearest-rank percentile on an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


def summary() -> dict:
    """Aggregate the session's telemetry.

    Returns::

        {
          "enabled": bool,
          "events": total events currently in the ring,
          "dropped": events evicted from the full ring (0 = none lost),
          "events_by_kind": {kind: n},
          "counts": {name: n},              # count() counters
          "bytes_by_kind": {kind: bytes},   # structural comm volumes
          "spans": {name: {"n", "total_s", "p50_s", "p95_s", "max_s"}},
        }

    Works (returns zeros) even when telemetry is disabled, so callers
    can embed it unconditionally.
    """
    evs = _recorder.events()
    by_kind: dict = {}
    for e in evs:
        k = e.get("kind", "?")
        by_kind[k] = by_kind.get(k, 0) + 1
    spans = {}
    for name, durs in _recorder.span_durations().items():
        ds = sorted(durs)
        spans[name] = {
            "n": len(ds),
            "total_s": round(sum(ds), 6),
            "p50_s": round(_percentile(ds, 0.50), 6),
            "p95_s": round(_percentile(ds, 0.95), 6),
            "max_s": round(ds[-1], 6) if ds else 0.0,
        }
    return {
        "enabled": _recorder.enabled(),
        "events": len(evs),
        "dropped": _recorder.dropped(),
        "events_by_kind": by_kind,
        "counts": _recorder.counters(),
        "bytes_by_kind": _recorder.bytes_by_kind(),
        "spans": spans,
    }
