"""Request-scoped trace context: ticket ids propagated through events.

The serving path (``batch.SolveSession``) answers many callers over one
event stream; without a request id the stream answers "what happened"
but not "what happened to MY solve". This module is the propagation
substrate: every submitted system gets a process-unique *ticket id*
(``new_ticket_id()``), the session enters a :func:`ticket_scope` around
each dispatch AND around each deferred retire (streaming dispatch
splits the two — the launch's pack/compile events and the retire's
``batch.dispatch``/requeue/terminal events carry the same lanes'
ids), and the recorder (``_recorder.record``) stamps every event
emitted inside the scope with the active ids — so a
``kernel.failover`` five layers down in a Pallas wrapper carries the
tickets whose solve it degraded, without any layer in between knowing
tickets exist.

Design rules:

* **contextvars, not globals.** The scope nests correctly across the
  requeue path (a fallback dispatch re-enters with just the requeued
  lanes' ids) and stays correct if a session is ever driven from
  multiple threads — each thread/task sees its own stack.
* **Replace semantics.** Entering a scope *replaces* the active id set
  rather than appending: a requeue dispatch is attributed to the lanes
  it actually solves, not the whole original bucket.
* **Zero overhead when telemetry is off.** The only reader is
  ``record()``, which is already gated on ``settings.telemetry``; the
  scope itself is two contextvar operations and only the instrumented
  serving path enters it.
* **Explicit fields win.** An event that already carries ``ticket`` or
  ``tickets`` is never overwritten — call sites that know the exact
  lanes (``batch.requeue``, ``batch.deadline``) stay authoritative.
"""

from __future__ import annotations

import contextlib
import contextvars
import itertools
import os
import threading

# ticket ids are process-unique and sortable: tk-<pid%0x10000 hex>-<seq>.
# The pid fragment keeps ids distinct when bench worker subprocesses
# append to the SAME records.jsonl as the parent.
_SEQ = itertools.count(1)
_SEQ_LOCK = threading.Lock()
_PREFIX = f"tk-{os.getpid() % 0x10000:04x}"

_TICKETS: contextvars.ContextVar[tuple] = contextvars.ContextVar(
    "sparse_tpu_tickets", default=()
)


def new_ticket_id() -> str:
    """A fresh process-unique ticket id (``tk-<pid>-<n>``)."""
    with _SEQ_LOCK:
        n = next(_SEQ)
    return f"{_PREFIX}-{n:06d}"


def current_tickets() -> tuple:
    """The active scope's ticket ids (empty tuple outside any scope)."""
    return _TICKETS.get()


@contextlib.contextmanager
def ticket_scope(*ids):
    """Make ``ids`` the active ticket set for the dynamic extent of the
    block (REPLACING any enclosing scope's ids — see module docstring).
    Events recorded inside gain a ``tickets`` field unless they carry
    their own. ``ticket_scope()`` with no ids clears the context."""
    token = _TICKETS.set(tuple(str(i) for i in ids))
    try:
        yield
    finally:
        _TICKETS.reset(token)


def annotate(ev: dict) -> dict:
    """Stamp the active ticket ids onto an event dict in place (the
    recorder's hook). Explicit ``ticket``/``tickets`` fields win; no
    allocation outside an active scope."""
    ids = _TICKETS.get()
    if ids and "tickets" not in ev and "ticket" not in ev:
        ev["tickets"] = list(ids)
    return ev
