"""Solver health monitoring: residual forensics for every Krylov solve.

The convergence questions a serving stack actually asks — "did that
solve blow up, stall, or diverge, and when" — need more than the final
iteration count. This module rides the *existing* per-iteration
telemetry taps (``linalg._make_iter_tap``, the fused-CG chunk fetch,
GMRES cycle fetches, and the batched loops' lane taps) to keep a
bounded residual history per solve, run three detectors, and emit
structured ``solver.anomaly`` events:

* **nonfinite** — ``||r||^2`` went NaN/Inf (breakdown, bad operator
  data, overflow);
* **divergence** — the residual grew ``DIVERGENCE_FACTOR`` past the
  best value seen (the solve is actively getting worse);
* **stagnation** — no meaningful improvement (relative ``STALL_RTOL``)
  for ``STALL_WINDOW`` consecutive observed iterations (singular or
  indefinite systems grinding to maxiter);
* **breakdown** — a Krylov scalar recurrence degenerated: BiCGStab's
  rho or omega hit exact zero while the residual is still nonzero (the
  recurrence silently ``where``-guards the division and stops making
  progress). Fed by :func:`observe_breakdown` from the solver's
  telemetry tap; the recovery policy engine
  (``sparse_tpu.resilience.policy``) escalates such solves to GMRES.

Each anomaly fires at most once per (reason, lane) per solve — a
diverging 10k-iteration solve is one event, not 10k — and also bumps
the always-on ``solver.anomalies`` metrics counters
(:mod:`._metrics`), so anomaly *counts* are scrapeable even when the
event ring has rotated. ``telemetry.last_solve_report()`` returns the
most recent solve's full report (history, anomalies, outcome).

Zero overhead when telemetry is off: every entry point's first
statement is the one ``settings.telemetry`` attribute check, and the
taps feeding this module only exist in instrumented traces.
"""

from __future__ import annotations

import collections
import math
import threading

import numpy as np

from ..config import settings
from . import _metrics, _recorder

#: max (iter, resid2) points kept per solve report
HISTORY_MAX = 256
#: iterations without meaningful improvement before "stagnation"
STALL_WINDOW = 40
#: relative improvement below this does not reset the stall window
STALL_RTOL = 1e-4
#: resid2 growth over the best seen that flags "divergence" (~1e4x ||r||)
DIVERGENCE_FACTOR = 1e8

_LOCK = threading.RLock()
_CURRENT = None
_LAST = None

# registered at import (telemetry/__init__ imports this module), so the
# anomaly counter is present in metrics_text() from the first scrape
_ANOMALIES = _metrics.counter("solver.anomalies")


class _Report:
    """Mutable per-solve state; dict-ified by :func:`last_solve_report`."""

    __slots__ = (
        "solver", "path", "lanes", "history", "best", "best_iter",
        "last_iter", "anomalies", "iters", "final_resid2", "converged",
        "_flags",
    )

    def __init__(self, solver: str, path: str, lanes: int | None = None):
        self.solver = solver
        self.path = path
        self.lanes = lanes
        self.history = collections.deque(maxlen=HISTORY_MAX)
        # scalars for unbatched solves; numpy arrays for lane stacks
        self.best = None
        self.best_iter = None
        self.last_iter = -1
        self.anomalies = []
        self.iters = None
        self.final_resid2 = None
        self.converged = None
        self._flags = set()

    def as_dict(self) -> dict:
        return {
            "solver": self.solver,
            "path": self.path,
            "lanes": self.lanes,
            "iters": self.iters,
            "final_resid2": self.final_resid2,
            "converged": self.converged,
            "anomalies": list(self.anomalies),
            "resid_history": [tuple(p) for p in self.history],
        }


def _anomaly(rep: _Report, reason: str, it, resid2, lane=None) -> None:
    """Record one anomaly, throttled to once per (reason, lane) per
    solve; mirrors into the event stream and the metrics registry."""
    key = (reason, lane)
    if key in rep._flags:
        return
    rep._flags.add(key)
    entry = {"reason": reason, "iter": it, "resid2": resid2}
    if lane is not None:
        entry["lane"] = lane
    rep.anomalies.append(entry)
    _ANOMALIES.inc()
    _metrics.counter("solver.anomalies.by_reason", reason=reason).inc()
    fields = {"solver": rep.solver, "reason": reason, "path": rep.path}
    if it is not None:
        fields["iter"] = int(it)
    if resid2 is not None:
        fields["resid2"] = resid2
    if lane is not None:
        fields["lane"] = int(lane)
    _recorder.record("solver.anomaly", **fields)


def _fresh(solver: str, path: str, lanes=None) -> _Report:
    global _CURRENT, _LAST
    rep = _Report(solver, path, lanes)
    if _CURRENT is not None:
        _LAST = _CURRENT
    _CURRENT = rep
    return rep


def _current_for(solver: str, path: str, it, lanes=None) -> _Report:
    """The active report, starting a new one when the observation can't
    belong to the current solve (different solver/path, or the iteration
    counter went backwards)."""
    rep = _CURRENT
    if (
        rep is None
        or rep.iters is not None  # previous solve already finalized
        or rep.solver != solver
        or rep.path != path
        or rep.lanes != lanes
        or (it is not None and it <= rep.last_iter)
    ):
        rep = _fresh(solver, path, lanes)
    return rep


def observe(solver: str, it: int, resid2: float, path: str = "device") -> None:
    """One (iteration, ||r||^2) observation of an unbatched solve —
    called from the solver loops' existing telemetry taps."""
    if not settings.telemetry:
        return
    with _LOCK:
        rep = _current_for(solver, path, it)
        rep.last_iter = it
        rep.history.append((int(it), float(resid2)))
        if not math.isfinite(resid2):
            _anomaly(rep, "nonfinite", it, float(resid2))
            return
        if rep.best is None or resid2 < rep.best * (1.0 - STALL_RTOL):
            rep.best = float(resid2)
            rep.best_iter = int(it)
            return
        if resid2 > rep.best * DIVERGENCE_FACTOR and rep.best > 0.0:
            _anomaly(rep, "divergence", it, float(resid2))
        if it - rep.best_iter >= STALL_WINDOW:
            _anomaly(rep, "stagnation", it, float(resid2))


def observe_breakdown(
    solver: str, it: int, abs_rho: float, abs_omega: float,
    resid2: float | None = None, path: str = "device",
) -> None:
    """One (|rho|, |omega|) observation from a BiCGStab-family tap. An
    exact zero in either scalar while the residual is still nonzero is
    the classic breakdown the recurrences ``where``-guard silently —
    flag it as a ``breakdown`` anomaly (throttled once per solve like
    every other reason). A zero scalar at zero residual is just exact
    convergence and stays silent."""
    if not settings.telemetry:
        return
    if resid2 is not None and (resid2 == 0.0 or not math.isfinite(resid2)):
        return  # converged exactly, or nonfinite (observe() flags that)
    rho_zero = not (abs_rho > 0.0) and math.isfinite(abs_rho)
    omega_zero = not (abs_omega > 0.0) and math.isfinite(abs_omega)
    if not (rho_zero or omega_zero):
        return
    with _LOCK:
        # like _current_for, but an observation at the CURRENT iteration
        # attaches to the live report (this tap fires alongside the same
        # iteration's resid2 observe(), which already advanced last_iter)
        rep = _CURRENT
        if (
            rep is None
            or rep.iters is not None
            or rep.solver != solver
            or rep.path != path
            or (it is not None and it < rep.last_iter)
        ):
            rep = _fresh(solver, path)
        rep.last_iter = max(rep.last_iter, int(it))
        _anomaly(rep, "breakdown", it, resid2)


def observe_lanes(
    solver: str, it: int, resid2s, tol2s=None, path: str = "batched"
) -> None:
    """Per-lane observation of a batched solve (one call per iteration,
    ``resid2s`` shaped ``(B,)``). Lanes already at their tolerance are
    excluded from stall/divergence checks — converged lanes FREEZE in
    the masked loops, which would otherwise read as stagnation."""
    if not settings.telemetry:
        return
    r = np.asarray(resid2s, dtype=np.float64)
    B = int(r.shape[0]) if r.ndim else 1
    r = r.reshape((B,))
    with _LOCK:
        rep = _current_for(solver, path, it, lanes=B)
        rep.last_iter = it
        rep.history.append((int(it), float(np.nanmax(r))))
        if rep.best is None:
            rep.best = np.full((B,), np.inf)
            rep.best_iter = np.zeros((B,), dtype=np.int64)
        done = np.zeros((B,), dtype=bool)
        if tol2s is not None:
            t = np.asarray(tol2s, dtype=np.float64).reshape((-1,))
            if t.shape[0] == B:
                with np.errstate(invalid="ignore"):
                    done = r <= t
        finite = np.isfinite(r)
        for lane in np.nonzero(~finite & ~done)[0]:
            _anomaly(rep, "nonfinite", it, float(r[lane]), lane=int(lane))
        with np.errstate(invalid="ignore"):
            improved = finite & (r < rep.best * (1.0 - STALL_RTOL))
        rep.best = np.where(improved, r, rep.best)
        rep.best_iter = np.where(improved, it, rep.best_iter)
        live = finite & ~done & ~improved
        with np.errstate(invalid="ignore"):
            diverged = live & (rep.best > 0) & (r > rep.best * DIVERGENCE_FACTOR)
        for lane in np.nonzero(diverged)[0]:
            _anomaly(rep, "divergence", it, float(r[lane]), lane=int(lane))
        stalled = live & (it - rep.best_iter >= STALL_WINDOW)
        for lane in np.nonzero(stalled)[0]:
            _anomaly(rep, "stagnation", it, float(r[lane]), lane=int(lane))


def end_solve(
    solver: str, iters, resid2=None, converged=None, path: str = "device"
) -> None:
    """Finalize the active report at solve completion (called from the
    ``solver.solve`` event sites). Runs a final nonfinite check so
    solves with no per-iteration visibility (TPU device loops) still
    flag a NaN outcome."""
    if not settings.telemetry:
        return
    with _LOCK:
        global _LAST, _CURRENT
        rep = _CURRENT
        if rep is None or rep.solver != solver or rep.iters is not None:
            rep = _Report(solver, path)
        rep.iters = int(iters) if iters is not None else None
        if resid2 is not None:
            rep.final_resid2 = float(resid2)
            if not math.isfinite(rep.final_resid2):
                _anomaly(rep, "nonfinite", rep.iters, rep.final_resid2)
        if converged is not None:
            rep.converged = bool(converged)
        _LAST = rep
        _CURRENT = None


def end_batch(solver: str, iters, resid2s, converged, path: str = "batched") -> None:
    """Finalize a batched solve from its per-lane outcome arrays: final
    nonfinite sweep per lane, then the report closes like
    :func:`end_solve`."""
    if not settings.telemetry:
        return
    r = np.asarray(resid2s, dtype=np.float64).reshape((-1,))
    it = np.asarray(iters).reshape((-1,))
    conv = np.asarray(converged).reshape((-1,))
    B = int(r.shape[0])
    with _LOCK:
        global _LAST, _CURRENT
        rep = _CURRENT
        if (
            rep is None or rep.solver != solver or rep.iters is not None
            or rep.lanes != B
        ):
            rep = _Report(solver, path, lanes=B)
        for lane in np.nonzero(~np.isfinite(r))[0]:
            _anomaly(
                rep, "nonfinite", int(it[lane]), float(r[lane]),
                lane=int(lane),
            )
        rep.iters = int(it.max(initial=0))
        rep.final_resid2 = float(np.nanmax(r)) if B else None
        rep.converged = bool(conv.all()) if B else None
        _LAST = rep
        _CURRENT = None


def last_solve_report() -> dict | None:
    """Dict view of the most recent solve's health report (the active
    solve if one is mid-flight), or ``None`` when nothing was observed
    (telemetry off, or no instrumented solve yet)."""
    with _LOCK:
        rep = _CURRENT if _CURRENT is not None else _LAST
        return rep.as_dict() if rep is not None else None


def reset() -> None:
    global _CURRENT, _LAST
    with _LOCK:
        _CURRENT = None
        _LAST = None
