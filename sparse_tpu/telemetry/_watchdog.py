"""SLO watchdog: an in-process rule engine over the always-on metrics.

The passive Axon surfaces (events, metrics, reports) answer operator
questions *when asked*; this module asks them continuously. A
:class:`Watchdog` evaluates declarative :class:`Rule`\\ s — SLO-miss
rate, anomaly rate, queue-depth saturation, device-occupancy floor,
vault quarantines, failover latches — against the always-on metrics
registry (:mod:`._metrics`) and the resilience latch state, on a
monotonic tick (``start()`` runs a daemon thread) and on demand
(``evaluate()``).

Rule semantics (docs/telemetry.md "Axon v5" has the operator table):

* **trigger / clear with hysteresis** — a rule fires when its value
  breaches ``trigger`` (direction per ``op``) for ``for_ticks``
  consecutive ticks, and clears only when the value is back on the good
  side of ``clear`` (a separate, less sensitive threshold) for
  ``clear_ticks`` ticks — so a level oscillating around the trigger
  produces ONE alert, not a flap storm.
* **cooldown** — after a clear, re-alerting is suppressed for
  ``cooldown_s`` seconds even if the trigger condition returns.
* **windowed rates** — the ``*_rate`` rule factories read counter
  *deltas* between ticks (the registry's counters are cumulative), and
  return ``None`` (skip the tick, streaks untouched) when the
  denominator didn't move — an idle session never alerts or clears on
  stale data.

Every alert transition bumps the always-on
``watchdog.alerts{rule,severity}`` counter and (telemetry enabled)
emits a ``watchdog.alert`` event; clears emit ``watchdog.clear``. The
live exporter's ``/alerts`` endpoint (:mod:`._serve`) serves
:func:`state`, and ``/healthz`` summarizes the active set.

Zero overhead by default: nothing ticks until a :class:`Watchdog` is
constructed, the engine only READS registry values (no device touch, no
dispatch-path hook anywhere), and with no watchdog :func:`state` is a
constant dict — the dispatch path's traces and host-sync counts are
untouched (pinned alongside the loadgen tests).
"""

from __future__ import annotations

import threading
import time

from . import _metrics, _recorder

__all__ = [
    "Rule",
    "Watchdog",
    "add_alert_hook",
    "anomaly_rate_rule",
    "current",
    "default_rules",
    "device_occupancy_rule",
    "failover_rule",
    "mesh_change_rule",
    "queue_depth_rule",
    "remove_alert_hook",
    "slo_miss_rate_rule",
    "state",
    "stop_watchdog",
    "vault_quarantine_rule",
    "watchdog",
]

_OPS = (">", "<")


class Rule:
    """One declarative alert rule: a sampled ``value`` callable plus the
    trigger/clear thresholds and flap-control knobs (module docstring).
    ``value()`` returning ``None`` skips the tick entirely."""

    __slots__ = ("name", "severity", "value", "trigger", "clear", "op",
                 "for_ticks", "clear_ticks", "cooldown_s")

    def __init__(self, name: str, value, trigger: float, *,
                 clear: float | None = None, op: str = ">",
                 severity: str = "warn", for_ticks: int = 1,
                 clear_ticks: int = 1, cooldown_s: float = 0.0):
        if op not in _OPS:
            raise ValueError(f"op must be one of {_OPS}, got {op!r}")
        self.name = str(name)
        self.severity = str(severity)
        self.value = value
        self.trigger = float(trigger)
        self.clear = self.trigger if clear is None else float(clear)
        self.op = op
        self.for_ticks = max(int(for_ticks), 1)
        self.clear_ticks = max(int(clear_ticks), 1)
        self.cooldown_s = max(float(cooldown_s), 0.0)

    def breached(self, v: float) -> bool:
        return v > self.trigger if self.op == ">" else v < self.trigger

    def cleared(self, v: float) -> bool:
        """On the good side of the *clear* threshold (hysteresis: for
        ``op='>'`` that is ``v <= clear``; for ``op='<'``,
        ``v >= clear``)."""
        return not (v > self.clear if self.op == ">" else v < self.clear)


class _RuleState:
    __slots__ = ("rule", "state", "streak", "clear_streak", "since",
                 "last_value", "alerts", "last_clear")

    def __init__(self, rule: Rule):
        self.rule = rule
        self.state = "ok"
        self.streak = 0
        self.clear_streak = 0
        self.since = None  # monotonic instant the current alert began
        self.last_value = None
        self.alerts = 0
        self.last_clear = None


class Watchdog:
    """The rule engine. Construct with a rule list (default:
    :func:`default_rules`), then either ``start()`` the monotonic tick
    thread or call ``evaluate()`` on demand (chaos drivers and tests do
    the latter for determinism)."""

    def __init__(self, rules=None, interval_s: float = 1.0):
        rules = list(rules) if rules is not None else default_rules()
        names = [r.name for r in rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names in {names}")
        self.interval_s = max(float(interval_s), 0.01)
        self.ticks = 0
        self.t0 = time.monotonic()
        self._states = {r.name: _RuleState(r) for r in rules}
        # reentrant: alert hooks (the flight recorder) run inside
        # evaluate()'s critical section and may read state() back
        self._lock = threading.RLock()
        self._thread = None
        self._stop = threading.Event()

    # -- evaluation --------------------------------------------------------
    def evaluate(self, now: float | None = None) -> list:
        """One tick over every rule; returns the transitions this tick
        caused (``[{"event": "alert"|"clear", "rule": ..., ...}]``).
        ``now`` (a monotonic instant) is injectable for tests."""
        now = time.monotonic() if now is None else float(now)
        transitions = []
        with self._lock:
            self.ticks += 1
            for st in self._states.values():
                r = st.rule
                try:
                    v = r.value()
                except Exception:  # noqa: BLE001 - a rule never kills the tick
                    v = None
                if v is None:
                    continue
                v = float(v)
                st.last_value = v
                if st.state == "ok":
                    if r.breached(v):
                        st.streak += 1
                        in_cooldown = (
                            st.last_clear is not None
                            and now - st.last_clear < r.cooldown_s
                        )
                        if st.streak >= r.for_ticks and not in_cooldown:
                            st.state = "firing"
                            st.since = now
                            st.alerts += 1
                            st.clear_streak = 0
                            transitions.append(self._alert(r, v))
                    else:
                        st.streak = 0
                else:  # firing
                    if r.cleared(v):
                        st.clear_streak += 1
                        if st.clear_streak >= r.clear_ticks:
                            active_s = now - (st.since or now)
                            st.state = "ok"
                            st.streak = 0
                            st.clear_streak = 0
                            st.since = None
                            st.last_clear = now
                            transitions.append(
                                self._clear(r, v, active_s)
                            )
                    else:
                        st.clear_streak = 0
        return transitions

    def _alert(self, r: Rule, v: float) -> dict:
        _metrics.counter(
            "watchdog.alerts",
            help="watchdog rule alert transitions (rule, severity labels)",
            rule=r.name, severity=r.severity,
        ).inc()
        _recorder.record(
            "watchdog.alert", rule=r.name, severity=r.severity,
            value=round(v, 6), trigger=r.trigger, op=r.op,
        )
        t = {"event": "alert", "rule": r.name, "severity": r.severity,
             "value": v, "trigger": r.trigger, "op": r.op}
        # the incident hook point (ISSUE 12): every ok -> firing
        # transition is offered to the registered alert hooks — the
        # flight recorder's postmortem capture rides this. A hook must
        # never break the tick (or the dispatch that triggered an
        # on-demand evaluate), so each one is isolated.
        for hook in list(_ALERT_HOOKS):
            try:
                hook(t)
            except Exception:  # noqa: BLE001 - hooks never kill an alert
                pass
        return t

    def _clear(self, r: Rule, v: float, active_s: float) -> dict:
        _metrics.counter(
            "watchdog.clears",
            help="watchdog rule clear transitions",
            rule=r.name,
        ).inc()
        _recorder.record(
            "watchdog.clear", rule=r.name, value=round(v, 6),
            active_s=round(active_s, 3),
        )
        return {"event": "clear", "rule": r.name, "active_s": active_s}

    # -- views -------------------------------------------------------------
    def active(self) -> list:
        """Names of currently-firing rules."""
        with self._lock:
            return sorted(
                n for n, st in self._states.items() if st.state == "firing"
            )

    def state(self) -> dict:
        """JSON-friendly engine state (the ``/alerts`` payload)."""
        now = time.monotonic()
        with self._lock:
            rules = []
            for st in self._states.values():
                r = st.rule
                row = {
                    "name": r.name,
                    "severity": r.severity,
                    "state": st.state,
                    "value": st.last_value,
                    "trigger": r.trigger,
                    "clear": r.clear,
                    "op": r.op,
                    "alerts": st.alerts,
                }
                if st.state == "firing" and st.since is not None:
                    row["active_s"] = round(now - st.since, 3)
                rules.append(row)
            return {
                "enabled": True,
                "running": bool(self._thread and self._thread.is_alive()),
                "interval_s": self.interval_s,
                "ticks": self.ticks,
                "active": sorted(
                    n for n, st in self._states.items()
                    if st.state == "firing"
                ),
                "rules": rules,
            }

    # -- the monotonic tick thread ----------------------------------------
    def start(self) -> "Watchdog":
        """Begin ticking on a daemon thread (idempotent)."""
        with self._lock:
            if self._thread is not None and self._thread.is_alive():
                return self
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, name="sparse-tpu-axon-watchdog",
                daemon=True,
            )
            self._thread.start()
        return self

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate()
            except Exception:  # noqa: BLE001 - the tick must survive
                pass

    def stop(self) -> None:
        self._stop.set()
        t = self._thread
        if t is not None:
            t.join(timeout=5)
        self._thread = None


# ---------------------------------------------------------------------------
# alert hooks (ISSUE 12): callbacks offered every ok -> firing transition
# ---------------------------------------------------------------------------
def _flight_hook(transition: dict) -> None:
    """The built-in hook: hand the transition to the incident flight
    recorder (:mod:`._flight`), which decides for itself whether capture
    is enabled/rate-limited. Imported lazily — the module cycle
    (_flight reads watchdog state into its bundles) stays one-way at
    import time."""
    from . import _flight

    _flight.on_alert_transition(transition)


_ALERT_HOOKS: list = [_flight_hook]


def add_alert_hook(fn) -> None:
    """Register a callback invoked (best-effort, exceptions swallowed)
    on every rule's ok -> firing transition with the transition dict
    (``{"event": "alert", "rule", "severity", "value", "trigger",
    "op"}``). Hooks are process-global: every Watchdog instance fires
    them."""
    if fn not in _ALERT_HOOKS:
        _ALERT_HOOKS.append(fn)


def remove_alert_hook(fn) -> None:
    """Unregister a previously added hook (idempotent; the built-in
    flight hook can be removed too — tests isolating capture do)."""
    try:
        _ALERT_HOOKS.remove(fn)
    except ValueError:
        pass


# ---------------------------------------------------------------------------
# rule factories (the default vocabulary; thresholds overridable)
# ---------------------------------------------------------------------------
def _windowed_rate(read_num, read_den, min_den: int = 1):
    """A value callable computing per-window ``Δnum/Δden`` from two
    cumulative readers; ``None`` until the denominator moved by at
    least ``min_den`` (idle windows neither alert nor clear)."""
    snap = {"num": None, "den": None}

    def value():
        num, den = float(read_num()), float(read_den())
        if snap["num"] is None:
            snap["num"], snap["den"] = num, den
            return None
        dn, dd = num - snap["num"], den - snap["den"]
        snap["num"], snap["den"] = num, den
        if dd < min_den:
            return None
        return dn / dd

    return value


def _windowed_rate_by_label(read_counts, min_den: int = 1):
    """Per-label-value windowed rate (Axon v7 satellite): ``read_counts``
    returns cumulative ``{label_value: (num, den)}``; the value callable
    returns the WORST label's ``Δnum/Δden`` this window — so one
    tenant's breach can't hide inside a healthy aggregate. Labels whose
    denominator didn't move by ``min_den`` are skipped; ``None`` when no
    label qualifies (or on the priming tick)."""
    snap = {"counts": None}

    def value():
        counts = {k: (float(n), float(d))
                  for k, (n, d) in read_counts().items()}
        prev, snap["counts"] = snap["counts"], counts
        if prev is None:
            return None
        worst = None
        for k, (n1, d1) in counts.items():
            n0, d0 = prev.get(k, (0.0, 0.0))
            dd = d1 - d0
            if dd < min_den:
                continue
            rate = (n1 - n0) / dd
            if worst is None or rate > worst:
                worst = rate
        return worst

    return value


def _windowed_delta(read):
    """A value callable computing the per-window delta of one cumulative
    reader (``None`` on the priming tick)."""
    snap = {"v": None}

    def value():
        v = float(read())
        if snap["v"] is None:
            snap["v"] = v
            return None
        dv, snap["v"] = v - snap["v"], v
        return dv

    return value


def _tenant_miss_counts() -> dict:
    """Cumulative ``{tenant: (misses, tickets)}``: ``""`` aggregates
    every ticket; named tenants ride the v7 ``usage.*`` metering
    families (batch/service.py)."""
    counts = {
        "": (
            float(_metrics.counter("batch.slo_misses").value),
            float(sum(
                h.count for h in _metrics.family("batch.ticket_latency")
            )),
        )
    }
    acc: dict = {}
    for m in _metrics.family("usage.tickets"):
        t = m.labels.get("tenant")
        if t and t != "-":
            acc.setdefault(t, [0.0, 0.0])[1] += float(m.value)
    for m in _metrics.family("usage.slo_misses"):
        t = m.labels.get("tenant")
        if t and t != "-":
            acc.setdefault(t, [0.0, 0.0])[0] += float(m.value)
    counts.update({t: (c[0], c[1]) for t, c in acc.items()})
    return counts


def slo_miss_rate_rule(trigger: float = 0.5, clear: float = 0.1,
                       severity: str = "page", min_tickets: int = 1,
                       per_tenant: bool = False, **kw) -> Rule:
    """Fraction of the window's resolved tickets that missed the session
    SLO (``batch.slo_misses`` over the ``batch.ticket_latency`` family's
    total observations). The v5 headline serving alert — superseded in
    :func:`default_rules` by the v7 burn-rate pair (``_budget``) but
    kept for explicit construction. ``per_tenant=True`` evaluates the
    worst tenant's window rate instead of the aggregate (Axon v7
    satellite)."""
    if per_tenant:
        value = _windowed_rate_by_label(
            _tenant_miss_counts, min_den=min_tickets
        )
    else:
        value = _windowed_rate(
            lambda: _metrics.counter("batch.slo_misses").value,
            lambda: sum(
                h.count for h in _metrics.family("batch.ticket_latency")
            ),
            min_den=min_tickets,
        )
    return Rule(
        "slo_miss_rate", value,
        trigger, clear=clear, op=">", severity=severity, **kw)


def anomaly_rate_rule(trigger: float = 0.0, clear: float = 0.0,
                      severity: str = "warn", **kw) -> Rule:
    """Solver anomalies (nonfinite/divergence/stagnation/breakdown)
    detected this window — any at all is worth an operator's look."""
    return Rule(
        "anomaly_rate",
        _windowed_delta(
            lambda: _metrics.counter("solver.anomalies").value
        ),
        trigger, clear=clear, op=">", severity=severity, **kw)


def queue_depth_rule(trigger: float = 512.0, clear: float | None = None,
                     severity: str = "warn", **kw) -> Rule:
    """Queued-request depth saturation (the ``batch.queue_depth``
    gauge): sustained depth past the trigger means arrivals outrun
    dispatch capacity."""
    return Rule(
        "queue_depth",
        lambda: _metrics.gauge("batch.queue_depth").value,
        trigger, clear=(trigger / 2.0 if clear is None else clear),
        op=">", severity=severity, **kw)


def device_occupancy_rule(floor: float = 0.25, clear: float = 0.5,
                          severity: str = "warn", **kw) -> Rule:
    """Mean per-device real-lane occupancy floor
    (``fleet.device_occupancy``), evaluated only in windows where
    dispatches actually advanced — an idle mesh is not an underutilized
    one."""
    disp = _windowed_delta(
        lambda: _metrics.counter("batch.dispatches").value
    )

    def value():
        moved = disp()
        if not moved:  # None (priming) or 0 dispatches this window
            return None
        occ = _metrics.label_values("fleet.device_occupancy", "device")
        vals = [
            v for v in occ.values()
            if isinstance(v, (int, float)) and not isinstance(v, bool)
        ]
        if not vals:
            return None
        return sum(vals) / len(vals)

    return Rule("device_occupancy", value, floor, clear=clear, op="<",
                severity=severity, **kw)


def vault_quarantine_rule(trigger: float = 0.0, severity: str = "warn",
                          **kw) -> Rule:
    """Vault artifacts quarantined this window (``vault.quarantined``):
    disk-tier corruption is being detected — check ``quarantine/``."""
    return Rule(
        "vault_quarantine",
        _windowed_delta(
            lambda: _metrics.counter("vault.quarantined").value
        ),
        trigger, op=">", severity=severity, **kw)


def mesh_change_rule(trigger: float = 0.0, severity: str = "warn",
                     **kw) -> Rule:
    """Elastic topology transitions this window (the always-on
    ``fleet.remeshes{outcome}`` counters, ISSUE 20): any executed
    remesh — shrink, grow, swap or a flap-guard latch — is an operator
    event, whether or not the migration succeeded. Summed across
    outcomes so a latched transition fires the same rule."""
    return Rule(
        "mesh_change",
        _windowed_delta(
            lambda: sum(
                float(m.value) for m in _metrics.family("fleet.remeshes")
            )
        ),
        trigger, op=">", severity=severity, **kw)


def failover_rule(severity: str = "page", **kw) -> Rule:
    """Latched Pallas→XLA kernel failovers (the resilience registry):
    fires while any kernel is serving on its fallback formulation and
    clears when a probe reinstates it."""

    def value():
        try:
            from ..resilience import failover

            return float(len(failover.latches()))
        except Exception:  # noqa: BLE001 - no resilience import yet
            return None

    return Rule("failover_latched", value, 0.0, op=">",
                severity=severity, **kw)


def default_rules() -> list:
    """The stock rule set (each factory's defaults; see the rule
    reference table in docs/telemetry.md). Axon v7: the instantaneous
    ``slo_miss_rate`` rule is replaced by the error-budget burn-rate
    pair (``slo_fast_burn`` pages, ``slo_slow_burn`` warns —
    :mod:`._budget`); the factory itself stays exported for explicit
    construction."""
    from . import _budget

    return [
        *_budget.default_rules(),
        anomaly_rate_rule(),
        queue_depth_rule(),
        device_occupancy_rule(),
        vault_quarantine_rule(),
        mesh_change_rule(),
        failover_rule(),
    ]


# ---------------------------------------------------------------------------
# the process singleton (what /alerts serves)
# ---------------------------------------------------------------------------
_LOCK = threading.Lock()
_WATCHDOG: Watchdog | None = None


def watchdog(rules=None, interval_s: float = 1.0) -> Watchdog:
    """Get-or-create the process watchdog (``telemetry.watchdog()``).
    An existing instance is returned as-is — stop it first to change
    rules. The instance does NOT tick until ``start()``."""
    global _WATCHDOG
    with _LOCK:
        if _WATCHDOG is None:
            _WATCHDOG = Watchdog(rules=rules, interval_s=interval_s)
        return _WATCHDOG


def current() -> Watchdog | None:
    """The live process watchdog, or ``None``."""
    return _WATCHDOG


def state() -> dict:
    """The ``/alerts`` payload: the process watchdog's state, or a
    disabled stub when none exists."""
    wd = _WATCHDOG
    if wd is None:
        return {"enabled": False, "running": False, "active": [],
                "rules": []}
    return wd.state()


def stop_watchdog() -> None:
    """Stop and drop the process watchdog (idempotent)."""
    global _WATCHDOG
    with _LOCK:
        wd, _WATCHDOG = _WATCHDOG, None
    if wd is not None:
        wd.stop()
