"""Chrome-trace / Perfetto export of a telemetry session.

``telemetry.export_trace(path)`` (or ``scripts/axon_trace.py`` over a
``records.jsonl``) writes the Trace Event Format JSON that
``ui.perfetto.dev`` and ``chrome://tracing`` open directly — the
timeline view the reference stack gets from Legion's profiler.

Layout: one *process* lane per subsystem (solver, kernels, comm,
plan_cache, batch, bench, spans, resilience, tickets) with named
*thread* tracks inside it (per solver, per event kind, per span family,
per ticket). Mapping:

* ``span`` events become complete (``"X"``) slices — the recorder stamps
  a span at *exit* with its duration, so the slice start is
  ``ts - dur_s`` and nesting falls out of containment (an inner span
  both starts later and ends earlier than its parent on the same
  track).
* ``solver.iter`` events additionally feed a per-solver ``resid2``
  counter track (``"C"``), so convergence plots right under the
  iteration marks.
* ``batch.ticket`` terminal events render the whole request as nested
  slices on the ticket's own track in the "tickets" lane: one
  end-to-end slice (the ticket's latency, ending at the event's
  timestamp) containing consecutive phase slices (queue wait → pack →
  compile → solve → readback) from the event's ``phases`` breakdown —
  the per-request view the reference stack's task timeline gives for
  free.
* everything else becomes an instant (``"i"``) event carrying its full
  field dict in ``args`` (ticket-scope ids ride along in ``args``, so
  the trace stays greppable per request).

The exporter is tolerant by construction: unknown kinds land in an
"other" lane, malformed events are skipped, and it never raises on
event *content* — a partial/trimmed session log still exports.
"""

from __future__ import annotations

import json
import math

from . import _recorder

#: subsystem lanes: ordered (pid, process name, kind-prefix tuple)
_LANES = (
    (1, "solver", ("solver.",)),
    (2, "kernels", ("autotune.", "kernel.", "coverage.")),
    (3, "comm", ("comm.",)),
    (4, "plan_cache", ("plan_cache.",)),
    (5, "batch", ("batch.",)),
    (6, "bench", ("bench.",)),
    (7, "spans", ("span",)),
    (8, "resilience", ("fault.", "checkpoint.", "resilience.")),
    (9, "session", ("session.",)),
    # incident flight recorder + profiler captures (ISSUE 12): the
    # postmortem markers render on their own lane, never "other"
    (12, "incidents", ("flight.", "profile.", "watchdog.", "loadgen.")),
)
_TICKETS_PID = 10
_OTHER_PID = 11
#: per-process lane namespacing stride: a merged multi-controller log
#: (scripts/axon_merge.py) renders process i's subsystem lanes at
#: ``pid + i * _PROC_STRIDE`` under a ``p<process_index>/`` name prefix
_PROC_STRIDE = 100

#: batch.ticket phase order, matching the serving path's breakdown
_TICKET_PHASES = ("queue", "pack", "compile", "solve", "readback")


def _lane_of(ev: dict) -> tuple:
    """(pid, thread-track name) for one event."""
    kind = ev.get("kind", "")
    if kind == "span":
        name = str(ev.get("name", "span"))
        return 7, name.split(".", 1)[0]
    if kind == "batch.ticket":
        return _TICKETS_PID, str(ev.get("ticket", "ticket"))
    for pid, _pname, prefixes in _LANES:
        for p in prefixes:
            if kind.startswith(p):
                if pid == 1:
                    return pid, str(ev.get("solver", kind))
                return pid, kind
    return _OTHER_PID, kind or "?"


def _num(v):
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    f = float(v)
    return f if math.isfinite(f) else None


def to_chrome_trace(events) -> dict:
    """Build the Trace Event Format dict from an event iterable.

    Events without a valid ``ts`` are skipped; nothing here raises on
    malformed content. Timestamps stay absolute epoch microseconds —
    Perfetto normalizes to the trace's own origin.

    When the events carry more than one ``pi`` (process_index — a merged
    multi-controller session from ``scripts/axon_merge.py``), every
    subsystem lane is replicated per process at ``pid + i *
    _PROC_STRIDE`` under a ``p<pi>/`` name prefix, so each controller's
    solver/comm/batch activity renders side by side on the one timeline.
    A single-process log renders exactly as before.
    """
    events = [e for e in events if isinstance(e, dict)]

    def _pi_of(ev):
        pi = ev.get("pi")
        return pi if isinstance(pi, int) and not isinstance(pi, bool) else None

    pis = sorted({p for p in (_pi_of(e) for e in events) if p is not None})
    multi = len(pis) > 1

    trace_events = []
    tids: dict = {}  # (pid, track name) -> tid int
    pids_seen = set()
    pid_meta: dict = {}  # final pid -> (base pid, pi or None)

    def tid_of(pid: int, track: str) -> int:
        key = (pid, track)
        t = tids.get(key)
        if t is None:
            t = len([1 for (p, _n) in tids if p == pid]) + 1
            tids[key] = t
        pids_seen.add(pid)
        return t

    for ev in events:
        ts = _num(ev.get("ts"))
        if ts is None:
            continue
        kind = ev.get("kind")
        if not isinstance(kind, str) or not kind:
            continue
        pid, track = _lane_of(ev)
        if multi:
            pi = _pi_of(ev)
            ns = pis.index(pi) if pi is not None else 0
            base = pid
            pid = pid + ns * _PROC_STRIDE
            pid_meta[pid] = (base, pis[ns] if pi is not None else pis[0])
        else:
            pid_meta[pid] = (pid, None)
        tid = tid_of(pid, track)
        ts_us = ts * 1e6
        args = {
            k: v for k, v in ev.items() if k not in ("kind", "ts")
        }
        if kind == "span":
            dur = _num(ev.get("dur_s"))
            dur_us = max(dur * 1e6, 0.0) if dur is not None else 0.0
            trace_events.append({
                "ph": "X", "name": str(ev.get("name", "span")),
                "cat": "span", "pid": pid, "tid": tid,
                "ts": ts_us - dur_us, "dur": dur_us, "args": args,
            })
            continue
        if kind == "batch.ticket":
            # one end-to-end slice ending at the terminal event's ts,
            # containing consecutive phase slices (queue -> ... ->
            # readback); malformed/missing phase fields just shrink the
            # breakdown — the total slice always renders
            phases = ev.get("phases")
            phases = phases if isinstance(phases, dict) else {}
            phase_us = []
            for p in _TICKET_PHASES:
                d = _num(phases.get(f"{p}_ms"))
                if d is not None and d > 0.0:
                    phase_us.append((p, d * 1e3))
            lat = _num(ev.get("latency_ms"))
            total_us = max(
                lat * 1e3 if lat is not None else 0.0,
                sum(d for _p, d in phase_us),
            )
            start_us = ts_us - total_us
            trace_events.append({
                "ph": "X", "name": f"ticket {ev.get('ticket', '?')}",
                "cat": "ticket", "pid": pid, "tid": tid,
                "ts": start_us, "dur": total_us, "args": args,
            })
            cursor = start_us
            for p, d in phase_us:
                trace_events.append({
                    "ph": "X", "name": p, "cat": "ticket.phase",
                    "pid": pid, "tid": tid, "ts": cursor, "dur": d,
                    "args": {"phase": p},
                })
                cursor += d
            continue
        trace_events.append({
            "ph": "i", "name": kind, "cat": kind.split(".", 1)[0],
            "pid": pid, "tid": tid, "ts": ts_us, "s": "t", "args": args,
        })
        if kind == "solver.iter":
            resid = _num(ev.get("resid2", ev.get("resid")))
            if resid is not None:
                trace_events.append({
                    "ph": "C", "name": f"resid2.{ev.get('solver', '?')}",
                    "pid": pid, "tid": tid, "ts": ts_us,
                    "args": {"resid2": resid},
                })

    trace_events.sort(key=lambda e: e["ts"])

    meta = []
    names = {pid: pname for pid, pname, _p in _LANES}
    names[_TICKETS_PID] = "tickets"
    names[_OTHER_PID] = "other"
    for pid in sorted(pids_seen):
        base, pi = pid_meta.get(pid, (pid, None))
        lane = names.get(base, "other")
        label = (
            f"sparse_tpu/p{pi}/{lane}" if pi is not None
            else f"sparse_tpu/{lane}"
        )
        meta.append({
            "ph": "M", "name": "process_name", "pid": pid, "tid": 0,
            "args": {"name": label},
        })
        meta.append({
            "ph": "M", "name": "process_sort_index", "pid": pid, "tid": 0,
            "args": {"sort_index": pid},
        })
    for (pid, track), tid in sorted(tids.items()):
        meta.append({
            "ph": "M", "name": "thread_name", "pid": pid, "tid": tid,
            "args": {"name": track},
        })
    return {
        "traceEvents": meta + trace_events,
        "displayTimeUnit": "ms",
        "otherData": {"source": "sparse_tpu.telemetry"},
    }


def read_events_jsonl(path: str) -> list:
    """Telemetry events of a records.jsonl (bench metric records — no
    ``kind`` — and unparseable lines are skipped, by the same contract
    as ``schema.validate_jsonl``)."""
    events = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict) and "kind" in ev:
                events.append(ev)
    return events


def export_trace(path: str, events=None, source: str | None = None) -> str:
    """Write the session as Chrome-trace JSON; returns ``path``.

    ``events`` defaults to the live in-memory ring; pass ``source=`` a
    records.jsonl path to export a logged session instead (works with
    telemetry disabled — this is offline analysis, not instrumentation).
    """
    if events is None:
        events = (
            read_events_jsonl(source) if source is not None
            else _recorder.events()
        )
    trace = to_chrome_trace(events)
    import os

    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, "w") as f:
        json.dump(trace, f)
    return path
