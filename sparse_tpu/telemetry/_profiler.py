"""Measured device-time profiling: jax.profiler capture + dispatch sampling.

Every "achieved vs roofline" number Axon reported before this module was
host wall-clock over *analytic* ``cost_analysis`` flops — ``jax.profiler``
existed only in comments (``coverage.py``). Ginkgo's batched-solver work
(TOMS'22, PAPERS.md §2) shows kernel-level *measured* timing is what
makes tuning actionable; this module adds the two measured surfaces:

* :func:`capture_trace` — an on-demand ``jax.profiler`` trace of a short
  live window, written into an incident bundle (``/debug/capture`` →
  ``profile/`` under the bundle dir). XLA's own profiler data
  (``*.xplane.pb`` + a Perfetto-openable ``*.trace.json.gz``) — the
  ground truth under the wall clocks.
* :func:`record_device_sample` — the always-on sink of the **sampled
  timed-dispatch path** in ``batch/service.py``: every Nth bucket
  dispatch (``SPARSE_TPU_PROFILE_EVERY``; 0 = off, the default) splits
  its solve wall clock at the dispatch-return boundary into *host* time
  (trace/dispatch overhead until the async call returns) and *device*
  time (async return until the results are ready — observed at the
  pipeline's retire), feeding the
  ``batch.program_device_ms{program}`` /
  ``batch.program_host_ms{program}`` histograms and the cost table's
  measured columns (:func:`._cost.note_device_time`) — the
  ``device_ms`` column in ``axon_report``'s roofline table. Under
  streaming dispatch (ISSUE 13) ``device_ms`` is the dispatch's
  *completion latency*: with several buckets in flight it includes
  device queueing behind earlier buckets, which is exactly the number
  a serving operator needs (time until this bucket's results existed),
  not a per-kernel device clock — :func:`capture_trace` remains the
  ground-truth kernel timeline.

Overhead discipline: sampling takes ONE extra ``time.monotonic()`` per
sampled dispatch and nothing at all when off; it never enters a traced
program (the compiled bucket programs are byte-identical with sampling
on or off — pinned by test) and adds no device syncs (the dispatch path
already blocks on its results).
"""

from __future__ import annotations

import glob as _glob
import os
import threading
import time

from . import _metrics, _recorder

__all__ = ["capture_trace", "record_device_sample"]

_LOCK = threading.Lock()
_ACTIVE = False  # jax.profiler allows one trace at a time, process-wide

_CAPTURES = _metrics.counter(
    "profile.captures",
    help="on-demand jax.profiler trace captures (ok or failed)",
)

_DEVICE_MS_HELP = (
    "measured device time (block_until_ready wait) per sampled bucket "
    "dispatch, milliseconds"
)
_HOST_MS_HELP = (
    "measured host time (dispatch call until async return) per sampled "
    "bucket dispatch, milliseconds"
)


def capture_trace(path: str, seconds: float = 0.2,
                  workload=None) -> dict:
    """Capture one ``jax.profiler`` trace window into ``path``.

    ``workload`` (a zero-arg callable) runs inside the window when
    given; otherwise the capture sleeps ``seconds`` so concurrently
    serving threads' device activity lands in the trace. Returns a
    JSON-friendly result dict (``ok``, ``dir``, ``files``, ``error``)
    and never raises — a missing/odd profiler degrades to
    ``ok=False``. One capture at a time process-wide (jax's own
    constraint); a concurrent request reports busy instead of crashing
    the running one."""
    global _ACTIVE
    out: dict = {"ok": False, "dir": path, "seconds": float(seconds)}
    with _LOCK:
        if _ACTIVE:
            out["error"] = "a profiler capture is already running"
            return out
        _ACTIVE = True
    t0 = time.perf_counter()
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.profiler.start_trace(path)
        try:
            if workload is not None:
                workload()
            else:
                time.sleep(max(float(seconds), 0.0))
        finally:
            jax.profiler.stop_trace()
        files = sorted(
            os.path.relpath(p, path)
            for p in _glob.glob(os.path.join(path, "**", "*"),
                                recursive=True)
            if os.path.isfile(p)
        )
        out["ok"] = True
        out["files"] = files[:16]
        out["wall_ms"] = round((time.perf_counter() - t0) * 1e3, 3)
    except Exception as e:  # noqa: BLE001 - capture is best-effort
        out["error"] = repr(e)[:200]
    finally:
        with _LOCK:
            _ACTIVE = False
    _CAPTURES.inc()
    _recorder.record(
        "profile.capture", ok=out["ok"], dir=path,
        **({"error": out["error"]} if "error" in out else {}),
    )
    return out


def record_device_sample(program: str, host_ms: float,
                         device_ms: float) -> None:
    """One sampled timed dispatch: feed the always-on per-program
    device/host histograms and the cost table's measured columns."""
    _metrics.histogram(
        "batch.program_device_ms", help=_DEVICE_MS_HELP, program=program,
    ).observe(device_ms)
    _metrics.histogram(
        "batch.program_host_ms", help=_HOST_MS_HELP, program=program,
    ).observe(host_ms)
    from . import _cost

    _cost.note_device_time(program, host_ms, device_ms)
