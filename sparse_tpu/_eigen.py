"""LOBPCG and nonsymmetric Arnoldi (eigs) — scipy.sparse.linalg drop-in
surface beyond the reference (its linalg.py has only symmetric eigsh,
linalg.py:1450).

TPU design notes:

- ``lobpcg`` is the most MXU-shaped eigensolver there is: every step is a
  tall-skinny [n, 3m] matmul + a tiny Rayleigh-Ritz eig. The block loop
  is host-driven (one device sync per iteration for the convergence
  check, matching the reference's per-cycle future reads) while all O(n)
  work is jitted device code.
- ``eigs`` is Krylov-Schur restarted Arnoldi: device matvecs + masked
  full-basis orthogonalization (same MXU-friendly projection the GMRES
  cycle uses), with the O(ncv^2) Schur reorder on host — control-plane
  work, exactly where the reference puts its FutureMap scans.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .coverage import track_provenance
from .utils import asjnp

__all__ = ["lobpcg", "eigs", "funm_multiply_krylov", "ArpackError",
           "ArpackNoConvergence"]


class ArpackError(RuntimeError):
    """scipy.sparse.linalg.ArpackError alias (raised by eigs/eigsh on
    irrecoverable iteration failures, e.g. Arnoldi breakdown below k)."""


class ArpackNoConvergence(ArpackError):
    """scipy alias: no convergence within maxiter; carries any converged
    partial results in ``eigenvalues``/``eigenvectors``."""

    def __init__(self, msg, eigenvalues=None, eigenvectors=None):
        super().__init__(msg)
        self.eigenvalues = eigenvalues if eigenvalues is not None else []
        self.eigenvectors = eigenvectors if eigenvectors is not None else []


def funm_multiply_krylov(f, A, b, *, assume_a="general", t=1.0, atol=0.0,
                         rtol=1e-6, restart_every_m=None, max_restarts=20):
    """Restarted Krylov evaluation of ``y = f(t A) b``
    (scipy.sparse.linalg.funm_multiply_krylov semantics).

    Arnoldi with full two-pass reorthogonalization (valid for both
    ``assume_a`` modes; the hermitian case simply enjoys a numerically
    tridiagonal projection) — device matvecs, MXU-shaped projections.
    ``f`` is applied on host to the accumulated block-Hessenberg of all
    cycles (the Eiermann-Ernst restart: f of the enlarged matrix makes
    each cycle's correction exact for the subspace so far), and this
    cycle's block of ``beta * f(tH) e1`` is lifted back through V.
    """
    from .linalg import make_linear_operator

    if assume_a not in ("general", "gen", "hermitian", "her"):
        raise ValueError(f"assume_a={assume_a!r} not in general/hermitian")
    A = make_linear_operator(A)
    n = A.shape[0]
    b = asjnp(b)
    dt = jnp.result_type(A.dtype, b.dtype, jnp.float32)
    b = b.astype(dt)
    m = int(restart_every_m) if restart_every_m else min(n, 20)
    m = max(1, min(m, n))
    beta = float(jnp.linalg.norm(b))
    if beta == 0:
        return jnp.zeros_like(b)

    y = jnp.zeros_like(b)
    H_full = np.zeros((0, 0), dtype=np.complex128)
    last_beta = 0.0
    v = b / beta
    for _ in range(int(max_restarts)):
        V = jnp.zeros((m + 1, n), dtype=dt).at[0].set(v)
        H = np.zeros((m + 1, m), dtype=np.complex128)
        # shared Arnoldi kernel (same code path as eigs); breakdown is
        # relative to each H column's own norm — NOT ||b||, which would
        # falsely trigger for large-norm b
        V, H, mdone = _arnoldi_extend(
            A.matvec, V, H, 0, m, breakdown_tol=1e-12
        )
        colnorm = float(np.linalg.norm(H[: mdone + 1, mdone - 1]))
        breakdown = float(abs(H[mdone, mdone - 1])) <= 1e-12 * colnorm
        # append this cycle's block to the accumulated Hessenberg
        k0 = H_full.shape[0]
        Hnew = np.zeros((k0 + mdone, k0 + mdone), dtype=np.complex128)
        Hnew[:k0, :k0] = H_full
        Hnew[k0:, k0:] = H[:mdone, :mdone]
        if k0 > 0:
            Hnew[k0, k0 - 1] = last_beta
        H_full = Hnew
        last_beta = H[mdone, mdone - 1]
        # f on the accumulated projection; lift this cycle's coefficients
        F = np.asarray(f(t * H_full), dtype=np.complex128)
        coeff = beta * F[k0: k0 + mdone, 0]
        real_out = not jnp.iscomplexobj(b)
        if real_out and np.abs(coeff.imag).max(initial=0.0) <= 1e-12 * max(
            np.abs(coeff).max(initial=0.0), 1e-300
        ):
            dy = V[:mdone].T @ jnp.asarray(coeff.real, dtype=dt)
        else:
            cdt = jnp.result_type(dt, jnp.complex64)
            dy = (V[:mdone].T.astype(cdt)
                  @ jnp.asarray(coeff, dtype=cdt))
            y = y.astype(cdt)
        y = y + dy
        dnorm = float(jnp.linalg.norm(dy))
        ynorm = float(jnp.linalg.norm(y))
        if dnorm <= max(float(atol), float(rtol) * max(ynorm, 1e-30)):
            return y
        if breakdown:
            return y  # invariant subspace: the evaluation is exact
        v = V[mdone]
    return y


def _ortho_cols(M):
    """Orthonormalize columns (plain unpivoted QR; a rank-deficient input
    yields arbitrary directions for the null columns)."""
    q, _ = jnp.linalg.qr(M)
    return q


@track_provenance
def lobpcg(A, X, B=None, M=None, Y=None, tol=None, maxiter=20,
           largest=True, retLambdaHistory=False,
           retResidualNormsHistory=False):
    """Locally optimal block preconditioned conjugate gradient
    (scipy.sparse.linalg.lobpcg, standard problem; ``B`` must be None —
    the generalized form is not implemented).

    Each iteration: one block SpMM, one [n, 3m] orthonormalization and a
    [3m, 3m] Rayleigh-Ritz — all MXU matmuls.
    """
    from .linalg import make_linear_operator

    if B is not None:
        raise NotImplementedError("lobpcg: generalized problem (B) not supported")
    A = make_linear_operator(A)
    n = A.shape[0]
    X = asjnp(X)
    if X.ndim != 2:
        raise ValueError("X must be [n, m]")
    m = X.shape[1]
    if n < 5 * m:
        raise ValueError("lobpcg: n < 5*m; use a dense eigensolver")
    dt = jnp.result_type(X.dtype, A.dtype, jnp.float32)
    X = X.astype(dt)
    rdt = jnp.zeros((), dt).real.dtype
    if tol is None:
        tol = float(np.sqrt(np.finfo(np.dtype(rdt)).eps)) * n
    Mop = None if M is None else make_linear_operator(M)
    if Y is not None:
        Y = _ortho_cols(asjnp(Y).astype(dt))

    def constrain(V):
        if Y is None:
            return V
        return V - Y @ (Y.conj().T @ V)

    def block_matvec(V):
        return A.matmat(V)  # one SpMM per block — MXU path

    @jax.jit
    def rayleigh_ritz(S):
        S = _ortho_cols(S)
        AS = block_matvec(S)
        T = S.conj().T @ AS
        T = 0.5 * (T + T.conj().T)
        w, C = jnp.linalg.eigh(T)
        return S, w, C

    X = _ortho_cols(constrain(X))
    P = None
    lam_hist, res_hist = [], []
    lam = None
    for _ in range(int(maxiter)):
        AX = block_matvec(X)
        G = X.conj().T @ AX
        lam = jnp.real(jnp.diagonal(G))
        R = AX - X * lam[None, :]
        rnorm = jnp.linalg.norm(R, axis=0)
        res_hist.append(np.asarray(rnorm))
        lam_hist.append(np.asarray(lam))
        if bool(jnp.all(rnorm <= tol)):
            break
        W = R if Mop is None else Mop.matmat(R)
        W = constrain(W)
        S = jnp.concatenate([X, W] + ([] if P is None else [P]), axis=1)
        S, w, C = rayleigh_ritz(S)
        idx = jnp.argsort(w)[::-1][:m] if largest else jnp.argsort(w)[:m]
        Cx = C[:, idx]
        X_new = S @ Cx
        # implicit P: the component of the new X outside the old X span
        P = X_new - X @ (X.conj().T @ X_new)
        pn = jnp.linalg.norm(P, axis=0)
        P = jnp.where(pn[None, :] > 0, P / jnp.where(pn == 0, 1, pn)[None, :], P)
        X = _ortho_cols(X_new)
    AX = block_matvec(X)
    lam = jnp.real(jnp.diagonal(X.conj().T @ AX))
    order = jnp.argsort(lam)[::-1] if largest else jnp.argsort(lam)
    lam, X = lam[order], X[:, order]
    out = (np.asarray(lam), np.asarray(X))
    if retLambdaHistory:
        out = out + (lam_hist,)
    if retResidualNormsHistory:
        out = out + (res_hist,)
    return out


# ---------------------------------------------------------------------------
# eigs: Krylov-Schur restarted Arnoldi
# ---------------------------------------------------------------------------
def _arnoldi_extend(matvec, V, H, start, ncv, breakdown_tol=0.0):
    """Extend an Arnoldi-like decomposition A V[:j] = V[:j+1] H[:j+1, :j]
    from column ``start`` to ``ncv``. V is [ncv+1, n] (rows are basis
    vectors), H is [ncv+1, ncv] (host numpy). Full reorthogonalization
    (two-pass MGS as masked matmuls — MXU-shaped like the GMRES cycle).
    Breakdown is declared when the residual norm falls below
    ``breakdown_tol`` RELATIVE to the new H column's norm — the natural
    per-column scale (never some unrelated vector's norm)."""
    for j in range(start, ncv):
        w = matvec(V[j])
        # two-pass projection against all current basis rows
        for _ in range(2):
            coeffs = jnp.conj(V[: j + 1]) @ w
            w = w - V[: j + 1].T @ coeffs
            H[: j + 1, j] += np.asarray(coeffs)
        beta = float(jnp.linalg.norm(w))
        H[j + 1, j] = beta
        colscale = float(np.linalg.norm(H[: j + 2, j]))
        if beta <= breakdown_tol * colscale:  # invariant subspace found
            return V, H, j + 1
        V = V.at[j + 1].set(w / beta)
    return V, H, ncv


def _sort_key(ritz, which):
    """Sort key per ARPACK ``which``: smaller key == more wanted."""
    which = which.upper()
    if which == "LM":
        return -np.abs(ritz)
    if which == "SM":
        return np.abs(ritz)
    if which == "LR":
        return -ritz.real
    if which == "SR":
        return ritz.real
    if which == "LI":
        return -ritz.imag
    if which == "SI":
        return ritz.imag
    raise ValueError(f"which={which!r} not in LM/SM/LR/SR/LI/SI")


def _select(ritz, which, k):
    return np.argsort(_sort_key(ritz, which), kind="stable")[:k]


@track_provenance
def eigs(A, k=6, which="LM", v0=None, ncv=None, maxiter=None, tol=0.0,
         return_eigenvectors=True):
    """Nonsymmetric eigenpairs by Krylov-Schur restarted Arnoldi
    (scipy.sparse.linalg.eigs semantics). Device matvecs and basis
    algebra; the [ncv, ncv] sorted-Schur reorder runs on host via
    ``scipy.linalg.schur`` (control-plane, like the reference's
    FutureMap scans). Returns complex eigenvalues (and vectors)."""
    import scipy.linalg as _sla

    from .linalg import make_linear_operator

    A = make_linear_operator(A)
    n = A.shape[0]
    if k >= n - 1:
        raise ValueError("k must be < n - 1 for Arnoldi; use a dense solver")
    if ncv is None:
        ncv = min(n - 1, max(2 * k + 1, 20))
    ncv = int(min(ncv, n - 1))
    if maxiter is None:
        maxiter = max(10, n // max(ncv - k, 1)) * 10
    dt = jnp.result_type(A.dtype, jnp.float32)
    cdt = jnp.complex64 if dt in (jnp.float32, jnp.complex64) else jnp.complex128
    rng = np.random.default_rng(0)
    if v0 is None:
        v0 = rng.standard_normal(n)
    v0 = asjnp(v0).astype(cdt)
    v0 = v0 / jnp.linalg.norm(v0)

    real_input = dt in (jnp.float32, jnp.float64)

    def matvec(v):
        if real_input:
            # keep the operator in its real dtype; complex basis = 2 matvecs
            return (
                A.matvec(jnp.real(v).astype(dt)).astype(cdt)
                + 1j * A.matvec(jnp.imag(v).astype(dt)).astype(cdt)
            )
        return A.matvec(v.astype(cdt))

    V = jnp.zeros((ncv + 1, n), dtype=cdt).at[0].set(v0)
    H = np.zeros((ncv + 1, ncv), dtype=np.complex128)
    V, H, mdone = _arnoldi_extend(matvec, V, H, 0, ncv)
    kk = k
    # default tol must match the COMPUTE precision (complex64 cannot hit
    # an f64-eps-derived target)
    ceps = float(np.finfo(np.dtype(jnp.zeros((), cdt).real.dtype)).eps)
    tol_eff = tol if tol > 0 else ceps ** (2 / 3)
    partial_evals = np.array([])
    _partial_count = 0
    _partial_vecs = np.zeros((n, 0), dtype=np.complex128)
    _partial_evals_best = np.array([])

    for _ in range(int(maxiter)):
        m = mdone
        if m < kk:
            # Arnoldi breakdown: an exact invariant subspace smaller than
            # the request — no k-dimensional Krylov space exists from v0
            raise ArpackError(
                f"eigs: Arnoldi breakdown at subspace dimension {m} < "
                f"k={kk}; the operator has an invariant subspace "
                "containing v0 — try a different v0 or smaller k"
            )
        Hm = H[:m, :m]
        beta_row = H[m, m - 1] if m < H.shape[0] else 0.0
        ritz_all = np.linalg.eigvals(Hm)
        # select by a magnitude-relative threshold on the `which` key —
        # NOT exact value matching: eigvals and schur are different LAPACK
        # paths and disagree in the low digits at large |lambda|
        key_all = _sort_key(ritz_all, which)
        kth = np.partition(key_all, kk - 1)[kk - 1]
        sel_tol = 1e-8 * max(float(np.max(np.abs(ritz_all))), 1e-30)

        def sort_fn(lam):
            return bool(
                _sort_key(np.asarray([lam]), which)[0] <= kth + sel_tol
            )

        T, Z, sdim = _sla.schur(Hm, output="complex", sort=sort_fn)
        sdim = max(int(sdim), 1)
        # Ritz pairs of the SELECTED block. The Schur sort's order inside
        # the block is arbitrary (a tie, e.g. a conjugate pair, can sit
        # ahead of a strictly-more-wanted value), so eigendecompose the
        # whole sdim block and re-select the k wanted pairs from it.
        bs = beta_row * Z[m - 1, :sdim]  # residual coupling row
        evals_all, Sv = np.linalg.eig(T[:sdim, :sdim])
        order = _select(evals_all, which, min(k, sdim))
        coup = np.abs(bs @ Sv[:, order])  # |A y - lam y| per Ritz vector
        scale = np.maximum(np.abs(evals_all[order]), 1e-30)
        # best Ritz pairs so far, with their residual couplings — the
        # partial results ArpackNoConvergence carries on failure. The
        # [n, p] host vectors are rebuilt only when the converged count
        # GROWS (at most k times total) — no per-cycle device matmul,
        # and no pinned reference to the old [ncv+1, n] basis.
        part_mask = coup <= tol_eff * scale
        partial_evals = evals_all[order][part_mask]
        if partial_evals.size > _partial_count:
            _partial_count = partial_evals.size
            small = Z[:, :sdim] @ Sv[:, order][:, part_mask]
            pv = np.asarray(V[:m].T @ jnp.asarray(small, dtype=cdt))
            nrm = np.linalg.norm(pv, axis=0, keepdims=True)
            _partial_vecs = pv / np.where(nrm == 0, 1.0, nrm)
            _partial_evals_best = partial_evals
        if sdim >= k and np.all(coup <= tol_eff * scale):
            evals = evals_all[order]
            vecs = np.asarray(V[:m].T @ jnp.asarray(
                Z[:, :sdim] @ Sv[:, order], dtype=cdt
            ))
            vecs = vecs / np.linalg.norm(vecs, axis=0, keepdims=True)
            # explicit residual gate: the coupling test can pass on a
            # ghost copy of a converged pair in low precision (seen at
            # complex64 with |lambda| ~ 1e6); k true matvecs are cheap
            vecs_d = jnp.asarray(vecs, dtype=cdt)
            R = jnp.stack(
                [matvec(vecs_d[:, i]) for i in range(k)], axis=1
            ) - vecs_d * jnp.asarray(evals, dtype=cdt)[None, :]
            rn = np.asarray(jnp.linalg.norm(R, axis=0))
            gate = 10 * tol_eff * np.maximum(np.abs(evals), 1e-30)
            if np.all(rn <= gate):
                if not return_eigenvectors:
                    return evals
                return evals, vecs
        # Krylov-Schur restart: keep the leading sdim Schur vectors
        keep = min(max(sdim, k), m - 1)
        Vnew = (V[:m].T @ jnp.asarray(Z[:, :keep], dtype=cdt)).T  # [keep, n]
        Hnew = np.zeros_like(H)
        Hnew[:keep, :keep] = T[:keep, :keep]
        Hnew[keep, :keep] = beta_row * Z[m - 1, :keep]
        V = jnp.zeros_like(V).at[:keep].set(Vnew).at[keep].set(V[m])
        H = Hnew
        V, H, mdone = _arnoldi_extend(matvec, V, H, keep, ncv)
    raise ArpackNoConvergence(
        f"eigs: no convergence to tol={tol_eff} within {maxiter} restarts",
        eigenvalues=_partial_evals_best,
        eigenvectors=_partial_vecs,
    )
