"""LaplacianNd — the N-D grid Laplacian LinearOperator with analytic
eigenpairs (scipy.sparse.linalg.LaplacianNd drop-in; beyond the
reference's surface).

TPU design: ``matvec`` applies the stencil as shifted adds on the
reshaped grid (pure XLA slice/pad fusion — no sparse gather at all), so
the operator is usable directly inside the device-resident solvers
(cg/minres/lobpcg) at full fusion. ``tosparse`` assembles the matrix via
``kronsum`` of 1-D stencils, the same identity the reference's PDE
examples build on.
"""

from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .linalg import LinearOperator
from .utils import asjnp

__all__ = ["LaplacianNd"]

_BCS = ("dirichlet", "neumann", "periodic")


def _eigvals_1d(n: int, bc: str) -> np.ndarray:
    i = np.arange(n)
    if bc == "dirichlet":
        return -4.0 * np.sin(np.pi * (i + 1) / (2 * (n + 1))) ** 2
    if bc == "neumann":
        return -4.0 * np.sin(np.pi * i / (2 * n)) ** 2
    return -4.0 * np.sin(np.pi * i / n) ** 2  # periodic


def _eigvecs_1d(n: int, bc: str) -> np.ndarray:
    """[n, n] columns = eigenvectors matching _eigvals_1d order."""
    j = np.arange(n)[:, None]
    i = np.arange(n)[None, :]
    if bc == "dirichlet":
        V = np.sin(np.pi * (j + 1) * (i + 1) / (n + 1))
    elif bc == "neumann":
        V = np.cos(np.pi * i * (j + 0.5) / n)
    else:  # periodic: real cos/sin combinations per frequency k
        V = np.zeros((n, n))
        for k in range(n):
            if k == 0:
                V[:, k] = 1.0
            elif 2 * k == n:  # Nyquist
                V[:, k] = np.cos(np.pi * j[:, 0])
            elif k <= n // 2:
                V[:, k] = np.cos(2 * np.pi * k * j[:, 0] / n)
            else:  # sin partner of frequency n-k (same eigenvalue)
                V[:, k] = np.sin(2 * np.pi * (n - k) * j[:, 0] / n)
    V /= np.linalg.norm(V, axis=0, keepdims=True)
    return V


class LaplacianNd(LinearOperator):
    """N-D grid Laplacian with ``dirichlet``/``neumann``/``periodic``
    boundary conditions (scipy.sparse.linalg.LaplacianNd surface:
    ``toarray``, ``tosparse``, ``eigenvalues(m)``, ``eigenvectors(m)``).

    Documented deviation: for a SIZE-1 axis under neumann/periodic,
    scipy's ``toarray`` adds -1 to the diagonal while its own
    ``eigenvalues`` formula says the axis contributes 0 (scipy's matrix
    and eigenvalues disagree there). Here all three views — ``matvec``,
    ``tosparse`` and the analytic eigenpairs — agree on the correct
    convention: a single cell has no neighbors, contribution 0.
    """

    def __init__(self, grid_shape, *, boundary_conditions="neumann",
                 dtype=np.int8):
        if boundary_conditions not in _BCS:
            raise ValueError(
                f"boundary_conditions must be one of {_BCS}, got "
                f"{boundary_conditions!r}"
            )
        self.grid_shape = tuple(int(g) for g in grid_shape)
        self.boundary_conditions = boundary_conditions
        n = int(np.prod(self.grid_shape))
        super().__init__((n, n), dtype=dtype)

    # -- operator application (pure shifted adds; fuses under jit) --------
    def matvec(self, x, out=None):
        x = asjnp(x)
        squeeze = x.ndim == 1
        cols = 1 if squeeze else x.shape[1]
        g = self.grid_shape
        bc = self.boundary_conditions
        v = x.reshape(g + (cols,))
        y = jnp.zeros_like(v)
        for ax in range(len(g)):
            n = g[ax]
            up = jnp.roll(v, -1, axis=ax)     # neighbor at i+1
            dn = jnp.roll(v, 1, axis=ax)      # neighbor at i-1
            if bc != "periodic":
                # zero the wrapped entries
                idx_last = [slice(None)] * v.ndim
                idx_last[ax] = n - 1
                up = up.at[tuple(idx_last)].set(0)
                idx_first = [slice(None)] * v.ndim
                idx_first[ax] = 0
                dn = dn.at[tuple(idx_first)].set(0)
            diag = jnp.full_like(v, -2.0)
            if bc == "neumann":
                # missing neighbor contributes its own cell: -1 on faces
                idx_last = [slice(None)] * v.ndim
                idx_last[ax] = n - 1
                diag = diag.at[tuple(idx_last)].set(-1.0)
                idx_first = [slice(None)] * v.ndim
                idx_first[ax] = 0
                diag = diag.at[tuple(idx_first)].add(1.0)
            y = y + up + dn + diag * v
        y = y.reshape((self.shape[0], cols))
        return y[:, 0] if squeeze else y

    rmatvec = matvec  # symmetric

    def matmat(self, X, out=None):
        return self.matvec(X)

    # -- assembly ---------------------------------------------------------
    def tosparse(self):
        from .module import diags, kronsum

        parts = []
        for n in self.grid_shape:
            o = np.full(n - 1, 1.0) if n > 1 else np.zeros(0)
            d = np.full(n, -2.0)
            if self.boundary_conditions == "neumann":
                # += (not =): a size-1 axis has BOTH faces on one cell,
                # whose diagonal must cancel to 0 (matvec agrees)
                d[0] += 1.0
                d[-1] += 1.0
            bands = [o, d, o]
            offs = [-1, 0, 1]
            if self.boundary_conditions == "periodic" and n == 1:
                # a single periodic cell is its own both neighbors: 0
                bands = [np.zeros(1)]
                offs = [0]
            elif self.boundary_conditions == "periodic" and n == 2:
                # wrap and direct neighbor coincide: coupling 2
                bands = [np.full(1, 2.0), d, np.full(1, 2.0)]
            elif self.boundary_conditions == "periodic" and n > 2:
                bands = [np.ones(1), o, d, o, np.ones(1)]
                offs = [-(n - 1), -1, 0, 1, n - 1]
            parts.append(diags(bands, offs, shape=(n, n)))
        L = parts[0]
        for p in parts[1:]:
            L = kronsum(p, L)  # kron(I, L) + kron(p, I): row-major order
        return L.tocsr()

    def toarray(self):
        return np.asarray(self.tosparse().todense()).astype(self.dtype)

    # -- analytic eigenpairs ---------------------------------------------
    def _all_eigvals(self):
        lams = [_eigvals_1d(n, self.boundary_conditions)
                for n in self.grid_shape]
        total = np.zeros(self.grid_shape)
        for ax, lam in enumerate(lams):
            shape = [1] * len(self.grid_shape)
            shape[ax] = len(lam)
            total = total + lam.reshape(shape)
        return total

    def eigenvalues(self, m=None):
        """All (or the ``m`` largest) eigenvalues, ascending (scipy)."""
        w = np.sort(self._all_eigvals().ravel())
        if m is None:
            return w
        return w[len(w) - int(m):]  # NOT w[-m:]: m=0 must give empty

    def eigenvectors(self, m=None):
        """Eigenvectors matching ``eigenvalues(m)``'s order, [N, m]."""
        total = self._all_eigvals().ravel()
        order = np.argsort(total)
        if m is not None:
            order = order[len(order) - int(m):]
        Vs = [_eigvecs_1d(n, self.boundary_conditions)
              for n in self.grid_shape]
        if len(order) == 0:
            return np.zeros((self.shape[0], 0))
        cols = []
        for flat in order:
            idx = np.unravel_index(flat, self.grid_shape)
            v = np.ones(1)
            for ax, i in enumerate(idx):
                v = np.kron(v, Vs[ax][:, i])
            cols.append(v / np.linalg.norm(v))
        return np.stack(cols, axis=1)
