"""sparse_tpu.ingest — the streaming matrix ingestion data plane (ISSUE 18).

Every pattern the serving stack handled before this subsystem was
constructed in-process; production traffic means unseen matrices
arriving constantly. This package reproduces the reference's canonical
init path — ``mmread -> distributed samplesort COO->CSR -> nnz-balanced
partitions`` (SURVEY §3.1, §2c-3; Legate Sparse SC'23 §1 builds its
whole runtime around this dependent-partitioning ingest) — as a
*serving-tier* pipeline riding the async machinery of ISSUE 13. Three
pieces:

* :mod:`.sort` — mesh-sharded bucketed ``all_to_all`` samplesort
  COO->CSR (``ingest_coo_to_csr``): local sort -> sample gather ->
  splitters -> ragged exchange -> merge (SURVEY §7's SORT_BY_KEY
  translation), accounted through :mod:`sparse_tpu.parallel.comm`
  SiteLedgers, with a single-device ``jax.lax.sort`` fast path for
  arrivals too small to shard, plus :func:`~.sort.balance` — the
  reference's ``balance()`` analog — producing nnz-balanced row
  partitions for skewed arrivals.
* :mod:`.fingerprint` — structure-only content keys
  (:func:`~.fingerprint.structure_key`) that dedup arrivals onto
  existing :class:`~sparse_tpu.batch.operator.SparsityPattern` objects:
  a hit means zero new compiles — the whole program-key chain (SELL
  packs, precond symbolics, bucket programs, autopilot decisions) is
  already warm behind the existing pattern object the plan cache keys
  on. The :class:`~.fingerprint.FingerprintIndex` persists
  ``structure key -> vault pattern key`` as a vault artifact, so dedup
  survives restarts: a fresh process recognizes a re-arrival before it
  has ever seen the matrix in-memory.
* :mod:`.onboard` — a background onboarding queue
  (:class:`~.onboard.Onboarder`, a bounded worker thread generalizing
  the warm-replay machinery) running the expensive pattern work —
  parse, sort, SELL pack, bucket prebuild, vault persistence — off the
  serving path. Exposed as
  :meth:`SolveSession.ingest(coo_or_path, ...) -> IngestTicket
  <sparse_tpu.batch.service.SolveSession.ingest>` with future-style
  ``ready``/``result()``, block/reject admission control
  (``SPARSE_TPU_INGEST_DEPTH`` / ``SPARSE_TPU_INGEST_ADMISSION``) and
  ``ingest.*`` telemetry kinds + counters so the watchdog, flight
  recorder and axon_report see onboarding as a first-class phase.

CI consumers: ``tests/test_ingest.py`` (quick lane), ``bench.py``'s
``ingest`` row (rows/s through sort->CSR->first-solve, dedup-hit vs
cold-pattern columns), and ``scripts/chaos_check.py`` scenario 14
(io faults + SIGKILL mid-onboarding). docs/ingest.md documents the
pipeline stages, fingerprint semantics and the onboarding lifecycle.
"""

from __future__ import annotations

from .fingerprint import (  # noqa: F401
    FingerprintIndex,
    structure_key,
)
from .onboard import (  # noqa: F401
    IngestAdmissionError,
    IngestError,
    IngestTicket,
    Onboarder,
)
from .sort import (  # noqa: F401
    balance,
    balance_stats,
    ingest_coo_to_csr,
)

__all__ = [
    "FingerprintIndex",
    "IngestAdmissionError",
    "IngestError",
    "IngestTicket",
    "Onboarder",
    "balance",
    "balance_stats",
    "ingest_coo_to_csr",
    "structure_key",
]
