"""Background pattern onboarding: the control plane of the ingest tier.

Everything expensive about an unseen matrix — parsing, the distributed
sort, the SELL pack, bucket-program compiles, vault persistence — runs
on ONE bounded daemon worker (:class:`Onboarder`, generalizing the
warm-replay thread of ISSUE 13) so the serving path never blocks on an
arrival. The serving-side handle is :class:`IngestTicket`: future-style
``ready``/``wait()``/``result()``, mirroring the solve tickets.

Lifecycle of one arrival (every transition is an ``ingest.onboard``
event; ``docs/ingest.md`` has the full state diagram)::

    queued -> parsing -> (dedup hit)  -> ready
                      -> (cold)      -> sorting -> onboarding -> ready
                      -> failed (after bounded retries)

* **dedup hit** (``ingest.dedup`` ``hit=True``): the arrival's
  structure key matches a pattern this session (or, through the vaulted
  :class:`~sparse_tpu.ingest.fingerprint.FingerprintIndex`, a previous
  process) already onboarded. The canonical values are grafted straight
  onto the existing pattern's CSR structure — no device sort, no pack,
  no compile: the first solve of the re-arrival is a pure plan-cache
  hit.
* **cold**: samplesort COO->CSR
  (:func:`~sparse_tpu.ingest.sort.ingest_coo_to_csr`), pattern
  registration into the session's coalescing map (the same
  ``setdefault`` the solve path races through, so
  onboard-vs-first-solve races converge on one canonical object), SELL
  pack, requested bucket prebuild, vault pattern + manifest note, and a
  fingerprint-index note so the NEXT process dedups this structure too.

Admission control mirrors the solve pipeline's: at
``max_depth`` queued arrivals, ``admission='block'`` waits for room and
``'reject'`` raises :class:`IngestAdmissionError` — backpressure is
explicit either way (``SPARSE_TPU_INGEST_DEPTH`` /
``SPARSE_TPU_INGEST_ADMISSION``).
"""

from __future__ import annotations

import itertools
import os
import threading
import time

import numpy as np

from .. import telemetry
from ..config import settings
from ..telemetry import _metrics
from . import fingerprint as fp_mod
from .sort import ingest_coo_to_csr

_ARRIVALS = _metrics.counter("ingest.arrivals")
_ONBOARDED = _metrics.counter("ingest.onboarded")
_DEDUP_HITS = _metrics.counter("ingest.dedup_hits")
_FAILED = _metrics.counter("ingest.failed")
_RETRIES = _metrics.counter("ingest.retries")
_QUEUE_DEPTH = _metrics.gauge("ingest.queue_depth")
# end-to-end onboarding latency (Axon v7 satellite), mirroring
# batch.ticket_latency: submit -> terminal, labeled by terminal state
_TICKET_LATENCY_HELP = (
    "end-to-end ingest onboarding latency in seconds (submit -> "
    "ready/failed)"
)

_ids = itertools.count(1)


class IngestError(RuntimeError):
    """An arrival that could not be onboarded (after retries)."""


class IngestAdmissionError(IngestError):
    """Rejected at the onboarding admission bound
    (``admission='reject'`` with ``max_depth`` arrivals queued)."""


class IngestTicket:
    """Future-style handle for one arrival moving through onboarding."""

    __slots__ = ("id", "source", "state", "dedup", "pattern", "csr",
                 "error", "submitted_s", "wall_ms", "tenant", "_event")

    def __init__(self, source: str, tenant: str | None = None):
        self.id = f"g{next(_ids)}"
        self.source = source
        self.tenant = tenant
        self.state = "queued"
        self.dedup: bool | None = None
        self.pattern = None
        self.csr = None
        self.error: Exception | None = None
        self.submitted_s = time.monotonic()
        self.wall_ms: float | None = None
        self._event = threading.Event()

    @property
    def ready(self) -> bool:
        """Terminal (``ready`` or ``failed``) — never blocks."""
        return self._event.is_set()

    def wait(self, timeout: float | None = None) -> bool:
        """Block until terminal (or timeout); True iff terminal."""
        return self._event.wait(timeout)

    def result(self, timeout: float | None = None) -> dict:
        """Block for the onboarding outcome: ``{pattern, csr, dedup,
        wall_ms, state}``. Raises :class:`IngestError` on failure or
        ``TimeoutError`` when the deadline passes first."""
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"ingest ticket {self.id} not onboarded within {timeout}s"
            )
        if self.error is not None:
            raise self.error
        return {
            "pattern": self.pattern, "csr": self.csr, "dedup": self.dedup,
            "wall_ms": self.wall_ms, "state": self.state,
        }

    def _finish(self, state: str) -> None:
        self.state = state
        self.wall_ms = round((time.monotonic() - self.submitted_s) * 1e3, 3)
        self._event.set()


def _as_coo(source):
    """Resolve one ingest source to host ``(rows, cols, vals, shape,
    kind)``: a MatrixMarket path, anything COO/CSR-shaped, or a raw
    ``(rows, cols, vals, shape)`` tuple."""
    if isinstance(source, (str, os.PathLike)):
        from ..io import read_coo_host

        rows, cols, vals, shape = read_coo_host(source)
        return rows, cols, vals, shape, "path"
    if isinstance(source, tuple) and len(source) == 4:
        rows, cols, vals, shape = source
        return (np.asarray(rows), np.asarray(cols), np.asarray(vals),
                (int(shape[0]), int(shape[1])), "coo")
    if hasattr(source, "row") and hasattr(source, "col"):
        return (np.asarray(source.row), np.asarray(source.col),
                np.asarray(source.data), source.shape, "coo")
    if hasattr(source, "tocoo"):
        c = source.tocoo()
        return (np.asarray(c.row), np.asarray(c.col), np.asarray(c.data),
                c.shape, "csr")
    raise TypeError(
        f"cannot ingest {type(source).__name__}: expected a MatrixMarket "
        "path, a COO/CSR-shaped array, or (rows, cols, vals, shape)"
    )


class Onboarder:
    """Bounded background onboarding queue bound to one SolveSession."""

    def __init__(self, session, max_depth: int | None = None,
                 admission: str | None = None,
                 retries: int | None = None):
        self.session = session
        self.max_depth = max(
            int(max_depth if max_depth is not None
                else settings.ingest_depth), 1,
        )
        self.admission = (
            admission if admission is not None else settings.ingest_admission
        )
        if self.admission not in ("block", "reject"):
            raise ValueError(
                f"admission must be 'block' or 'reject', "
                f"got {self.admission!r}"
            )
        self.retries = max(
            int(retries if retries is not None else settings.ingest_retries),
            0,
        )
        self.index = fp_mod.FingerprintIndex()
        self._queue: list = []
        self._cond = threading.Condition()
        self._active = 0
        self._closed = False
        self._counts = {"onboarded": 0, "dedup_hits": 0, "failed": 0,
                        "retries": 0}
        self._thread = threading.Thread(
            target=self._worker, name="sparse-tpu-onboard", daemon=True
        )
        self._thread.start()

    # -- serving-side API ---------------------------------------------------
    def submit(self, source, *, bucket: int = 1, dtype=np.float64,
               num_shards: int | None = None,
               tenant: str | None = None) -> IngestTicket:
        """Queue one arrival; returns its ticket immediately (admission
        permitting). ``bucket``/``dtype`` shape the prebuilt program a
        cold pattern gets ahead of its first solve; ``tenant`` attributes
        the onboarding work in the v7 ``usage.*`` metering families."""
        label = (
            os.fspath(source) if isinstance(source, (str, os.PathLike))
            else type(source).__name__
        )
        ticket = IngestTicket(label, tenant=tenant)
        with self._cond:
            if self._closed:
                raise IngestError("onboarder is closed")
            while len(self._queue) >= self.max_depth:
                if self.admission == "reject":
                    _metrics.counter(
                        "ingest.admissions", mode="reject"
                    ).inc()
                    raise IngestAdmissionError(
                        f"ingest queue at max_depth={self.max_depth} "
                        f"(admission='reject')"
                    )
                _metrics.counter("ingest.admissions", mode="block").inc()
                self._cond.wait(0.05)
                if self._closed:
                    raise IngestError("onboarder is closed")
            self._queue.append(
                (ticket, source, int(bucket), np.dtype(dtype), num_shards)
            )
            depth = len(self._queue)
            self._cond.notify_all()
        _ARRIVALS.inc()
        _QUEUE_DEPTH.set(depth)
        if telemetry.enabled():
            telemetry.record(
                "ingest.arrive", ticket=ticket.id, source=label,
                queue_depth=depth,
            )
        return ticket

    def drain(self, timeout: float = 120.0) -> bool:
        """Block until the queue is empty and the worker idle."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._queue or self._active:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cond.wait(min(left, 0.1))
        return True

    def close(self, timeout: float = 10.0) -> None:
        """Stop accepting arrivals and join the worker (queued items
        still complete)."""
        with self._cond:
            self._closed = True
            self._cond.notify_all()
        self._thread.join(timeout)

    def stats(self) -> dict:
        with self._cond:
            return {
                "queued": len(self._queue),
                "active": self._active,
                "max_depth": self.max_depth,
                "admission": self.admission,
                "index_entries": len(self.index),
                **self._counts,
            }

    # -- worker -------------------------------------------------------------
    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._closed:
                    self._cond.wait(0.25)
                if not self._queue:
                    if self._closed:
                        return
                    continue
                item = self._queue.pop(0)
                self._active = 1
                _QUEUE_DEPTH.set(len(self._queue))
            try:
                # ticket-scope the whole onboarding so nested events
                # (comm.sort, vault.store, plan_cache.*) carry the
                # originating ingest ticket id, mirroring the solve path
                with telemetry.ticket_scope(item[0].id):
                    self._process(*item)
            finally:
                with self._cond:
                    self._active = 0
                    self._cond.notify_all()

    def _finalize(self, ticket, state: str) -> None:
        """Terminal bookkeeping shared by ready/failed: stamp the
        ticket, observe the always-on latency histogram, meter the
        tenant's arrival and emit the ``ingest.ticket`` terminal event
        (the ingest mirror of ``batch.ticket``)."""
        ticket._finish(state)
        labels = {"state": state}
        if ticket.tenant:
            labels["tenant"] = ticket.tenant
        _metrics.histogram(
            "ingest.ticket_latency", help=_TICKET_LATENCY_HELP, **labels
        ).observe(ticket.wall_ms / 1e3)
        _metrics.counter(
            "usage.ingest",
            help="ingest arrivals resolved, per tenant (v7 metering)",
            tenant=ticket.tenant or "-", state=state,
        ).inc()
        if telemetry.enabled():
            telemetry.record(
                "ingest.ticket", ticket=ticket.id, state=state,
                latency_ms=ticket.wall_ms,
                **({"tenant": ticket.tenant} if ticket.tenant else {}),
            )

    def _process(self, ticket, source, bucket, dtype, num_shards) -> None:
        last_err = None
        for attempt in range(self.retries + 1):
            if attempt:
                with self._cond:
                    self._counts["retries"] += 1
                _RETRIES.inc()
                if telemetry.enabled():
                    telemetry.record(
                        "ingest.onboard", ticket=ticket.id, state="retry",
                        wall_ms=round(
                            (time.monotonic() - ticket.submitted_s) * 1e3, 3
                        ),
                    )
            try:
                self._onboard(ticket, source, bucket, dtype, num_shards)
                return
            except Exception as e:  # noqa: BLE001 - arrival isolation
                last_err = e
        ticket.error = IngestError(
            f"ingest {ticket.id} ({ticket.source}) failed after "
            f"{self.retries + 1} attempts: {last_err}"
        )
        ticket.error.__cause__ = last_err
        with self._cond:
            self._counts["failed"] += 1
        _FAILED.inc()
        self._finalize(ticket, "failed")
        if telemetry.enabled():
            telemetry.record(
                "ingest.onboard", ticket=ticket.id, state="failed",
                wall_ms=ticket.wall_ms,
            )

    def _onboard(self, ticket, source, bucket, dtype, num_shards) -> None:
        from ..batch.operator import SparsityPattern

        ticket.state = "parsing"
        rows, cols, vals, shape, _kind = _as_coo(source)
        crows, ccols, cvals = fp_mod.canonicalize_coo(rows, cols, vals, shape)
        skey = fp_mod.structure_key(crows, ccols, shape, canonical=True)
        fp = ((int(shape[0]), int(shape[1])), int(crows.shape[0]), skey)

        pattern = self.session._patterns.get(fp)
        if pattern is None:
            # restart-surviving dedup: a previous process may have
            # onboarded this structure — the vaulted index knows
            pkey = self.index.lookup(skey)
            if pkey is not None:
                from .. import vault

                pat = vault.load_pattern(pkey)
                if pat is not None and pat.fingerprint == fp:
                    pattern = self.session._patterns.setdefault(
                        pat.fingerprint, pat
                    )
        hit = pattern is not None
        if telemetry.enabled():
            telemetry.record(
                "ingest.dedup", ticket=ticket.id, hit=bool(hit),
                fingerprint=skey[:12],
            )
        if hit:
            # structure equality means the canonical value order IS the
            # pattern's nnz order: graft values, skip sort/pack/compile
            import sparse_tpu

            with self._cond:
                self._counts["dedup_hits"] += 1
            _DEDUP_HITS.inc()
            ticket.pattern = pattern
            ticket.csr = sparse_tpu.csr_array.from_parts(
                cvals, pattern.indices, pattern.indptr, pattern.shape
            )
            ticket.dedup = True
            self._finalize(ticket, "ready")
            if telemetry.enabled():
                telemetry.record(
                    "ingest.onboard", ticket=ticket.id, state="ready",
                    wall_ms=ticket.wall_ms,
                )
            return

        # cold pattern: the full data plane
        ticket.state = "sorting"
        csr = ingest_coo_to_csr(crows, ccols, cvals, shape, num_shards)
        ticket.state = "onboarding"
        pat = SparsityPattern.from_csr(csr)
        pattern = self.session._patterns.setdefault(pat.fingerprint, pat)
        pattern.sell_pack()
        try:
            self.session._prebuild(pattern, self.session.solver,
                                   int(bucket), dtype)
        except Exception:  # noqa: BLE001 - prebuild is an optimization
            pass
        from .. import vault

        pkey = None
        if vault.enabled():
            pkey = vault.store_pattern(pattern)
            vault.note_program(
                pattern, self.session.solver, int(bucket), np.dtype(dtype).str
            )
        if pkey is None:
            from ..vault import _codecs

            pkey = _codecs.pattern_key(pattern)
        self.index.note(skey, pkey)
        with self._cond:
            self._counts["onboarded"] += 1
        _ONBOARDED.inc()
        ticket.pattern = pattern
        ticket.csr = csr
        ticket.dedup = False
        self._finalize(ticket, "ready")
        if telemetry.enabled():
            telemetry.record(
                "ingest.onboard", ticket=ticket.id, state="ready",
                wall_ms=ticket.wall_ms,
            )
