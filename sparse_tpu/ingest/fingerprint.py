"""Structure-only arrival fingerprints + the persisted dedup index.

The dedup contract: two arrivals with the same *structure* (shape +
sorted, duplicate-collapsed coordinate set) must produce the same key,
regardless of value dtype, entry order, or duplicate coordinates in the
raw COO stream. The key is bit-identical to the sha1 half of
:attr:`SparsityPattern.fingerprint
<sparse_tpu.batch.operator.SparsityPattern.fingerprint>` — the hash the
:class:`~sparse_tpu.batch.service.SolveSession` already coalesces
same-pattern requests on — so an ingest-path hit and a solve-path hit
land on the SAME canonical pattern object, and with it the same
plan-cache identity key, SELL pack, precond symbolics, bucket programs
and autopilot decisions. Hit ⇒ zero new compiles.

:class:`FingerprintIndex` persists ``structure key -> vault pattern
key`` as a single pure-meta vault artifact (kind ``ingest_fpindex``),
so dedup survives restarts: a fresh process recognizes a re-arrival it
has never held in memory, loads the pattern structure from the vault,
and serves it over programs the warm-start manifest already replayed.
Best-effort like every vault write — a missing/corrupt index degrades
to a cold onboard, never an error.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

INDEX_KIND = "ingest_fpindex"
INDEX_KEY = "fpindex"


def canonicalize_coo(rows, cols, vals, shape):
    """Host-side canonical form of a raw COO arrival: lexicographically
    (row, col)-sorted with duplicate coordinates summed — the same
    collapse rule as :func:`parallel.sort.coo_to_csr_distributed
    <sparse_tpu.parallel.sort.coo_to_csr_distributed>`, so the structure
    key computed here matches the pattern that conversion builds.
    Returns ``(rows, cols, vals)`` (``vals`` is ``None`` in, ``None``
    out — the structure-only path never touches values)."""
    rows = np.asarray(rows, dtype=np.int64).reshape(-1)
    cols = np.asarray(cols, dtype=np.int64).reshape(-1)
    m, n = int(shape[0]), int(shape[1])
    if rows.shape[0] and (
        rows.min() < 0 or rows.max() >= m or cols.min() < 0 or cols.max() >= n
    ):
        raise ValueError(
            f"coordinate outside {m}x{n} shape "
            f"(rows in [{rows.min() if rows.size else 0}, "
            f"{rows.max() if rows.size else 0}])"
        )
    order = np.lexsort((cols, rows))
    rows, cols = rows[order], cols[order]
    if rows.shape[0]:
        is_new = np.concatenate(
            [[True], (rows[1:] != rows[:-1]) | (cols[1:] != cols[:-1])]
        )
    else:
        is_new = np.zeros((0,), dtype=bool)
    if vals is not None:
        vals = np.asarray(vals).reshape(-1)[order]
        if rows.shape[0] and not is_new.all():
            seg = np.cumsum(is_new) - 1
            uvals = np.zeros(int(seg[-1]) + 1, dtype=vals.dtype)
            np.add.at(uvals, seg, vals)
            vals = uvals
    return rows[is_new], cols[is_new], vals


def structure_key(rows, cols, shape, *, canonical: bool = False) -> str:
    """Structure-only content key of a COO arrival: the sha1 hex of the
    canonical CSR structure (shape + indptr + indices), computed WITHOUT
    building values or a pattern object. Equals
    ``SparsityPattern.fingerprint[2]`` of the pattern
    :func:`~sparse_tpu.ingest.sort.ingest_coo_to_csr` would assemble
    from the same coordinates. ``canonical=True`` skips the
    canonicalization (the caller already holds sorted, deduped
    coordinates — the onboarder's path)."""
    if not canonical:
        rows, cols, _ = canonicalize_coo(rows, cols, None, shape)
    rows = np.asarray(rows, dtype=np.int64).reshape(-1)
    cols = np.asarray(cols, dtype=np.int64).reshape(-1)
    m, n = int(shape[0]), int(shape[1])
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, rows + 1, 1)
    indptr = np.cumsum(indptr)
    h = hashlib.sha1()
    h.update(np.int64(m).tobytes())
    h.update(np.int64(n).tobytes())
    h.update(indptr.astype(np.int64).tobytes())
    h.update(cols.astype(np.int64).tobytes())
    return h.hexdigest()


class FingerprintIndex:
    """The restart-surviving half of dedup: an in-memory ``structure key
    -> vault pattern key`` map mirrored into one vault artifact.

    Thread-safe (the onboarder worker notes entries while the serving
    thread looks arrivals up). Every mutation re-deposits the full map —
    the index is tiny (two hex strings per distinct pattern ever seen)
    and the vault write is atomic-rename, so a crash mid-note leaves
    the previous consistent index, never a torn one."""

    def __init__(self, autoload: bool = True):
        self._map: dict[str, str] = {}
        self._lock = threading.Lock()
        if autoload:
            self.load()

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def load(self) -> int:
        """Merge the vaulted index into memory (fresh-process replay);
        returns entries loaded. A disabled vault or corrupt artifact
        loads nothing — dedup degrades to in-memory only."""
        from .. import vault

        obj = vault.fetch(INDEX_KIND, INDEX_KEY)
        if not isinstance(obj, dict):
            return 0
        loaded = {str(k): str(v) for k, v in obj.items()}
        with self._lock:
            self._map.update(loaded)
        return len(loaded)

    def lookup(self, key: str) -> str | None:
        """Vault pattern key for a structure key, or ``None`` (cold)."""
        with self._lock:
            return self._map.get(key)

    def note(self, key: str, pattern_key: str) -> None:
        """Record one onboarded structure and persist the index."""
        from .. import vault

        with self._lock:
            if self._map.get(key) == pattern_key:
                return
            self._map[key] = pattern_key
            snapshot = dict(self._map)
        vault.deposit(INDEX_KIND, INDEX_KEY, snapshot)

    def snapshot(self) -> dict:
        with self._lock:
            return dict(self._map)
