"""Ingest-tier COO->CSR: sharded samplesort + nnz-balanced partitions.

The data-plane half of the ingest pipeline (SURVEY §3.1): an arriving
coordinate stream becomes a canonical CSR through a distributed sort.
Two routes, chosen by the serving mesh:

* **mesh route** — :func:`parallel.sort.coo_to_csr_distributed
  <sparse_tpu.parallel.sort.coo_to_csr_distributed>`: the reference's
  samplesort shape (local sort -> regular-sample allgather -> splitter
  selection -> ``jax.lax.ragged_all_to_all`` bucket exchange -> merge,
  SURVEY §7's SORT_BY_KEY translation), with every collective accounted
  through the ``sort.sample1``/``sort.sample2`` SiteLedgers of
  :mod:`sparse_tpu.parallel.comm` and an odd-even transposition
  fallback when heavy duplicate keys break the regular-sampling bucket
  bound.
* **single-device fast path** — one ``jax.lax.sort`` over the fused
  ``row*n + col`` key (no shard_map, no collectives, no ledger
  traffic): the right shape for arrivals too small to shard, and the
  only shape on a single-device mesh.

Either route collapses duplicate coordinates (summing values — the
reference's SORTED_COORDS_TO_COUNTS discipline) and returns a
:class:`~sparse_tpu.csr.csr_array`.

:func:`balance` is the reference's ``balance()`` analog (SURVEY §2c-3):
an nnz-balanced row partition for skewed arrivals, where the uniform
``m/S`` row split the mesh would otherwise use puts one shard behind a
handful of dense rows. It is a *partition map* (S+1 row boundaries),
the ingest-side input to row-sharded placement — :func:`balance_stats`
quantifies how skewed the uniform split would have been.
"""

from __future__ import annotations

import time

import numpy as np

from .. import telemetry


def _dedup_sorted(srows, scols, svals, shape):
    """Collapse duplicate (row, col) pairs of a lex-sorted stream (sum)
    and assemble the CSR — the SORTED_COORDS_TO_COUNTS + nnz_to_pos
    scan shared by both sort routes."""
    import sparse_tpu

    m = int(shape[0])
    if srows.shape[0]:
        is_new = np.concatenate(
            [[True], (srows[1:] != srows[:-1]) | (scols[1:] != scols[:-1])]
        )
        seg = np.cumsum(is_new) - 1
        uvals = np.zeros(int(seg[-1]) + 1, dtype=svals.dtype)
        np.add.at(uvals, seg, svals)
        urows = srows[is_new]
        ucols = scols[is_new]
    else:
        urows, ucols, uvals = srows, scols, svals
    indptr = np.zeros(m + 1, dtype=np.int64)
    np.add.at(indptr, urows + 1, 1)
    indptr = np.cumsum(indptr)
    return sparse_tpu.csr_array.from_parts(
        uvals, ucols, indptr, (m, int(shape[1]))
    )


def _sort_single_device(rows, cols, vals, shape):
    """The single-device fast path: one ``jax.lax.sort`` over the fused
    key (requires ``m*n`` within int32 — the caller routes wider shapes
    through the two-pass distributed radix composition)."""
    import jax
    import jax.numpy as jnp

    n = int(shape[1])
    keys = np.asarray(rows, np.int32) * np.int32(n) + np.asarray(
        cols, np.int32
    )
    sk, sv = jax.lax.sort(
        (jnp.asarray(keys), jnp.asarray(vals)), num_keys=1, is_stable=True
    )
    sk = np.asarray(sk).astype(np.int64)
    svals = np.asarray(sv)
    return sk // n, sk % n, svals


def ingest_coo_to_csr(rows, cols, vals, shape, num_shards: int | None = None):
    """Canonical ingest conversion: raw COO arrays (host) -> CSR.

    ``num_shards=None`` uses the default mesh; ``1`` (or a single-device
    mesh, or ``settings.force_serial``) takes the ``jax.lax.sort`` fast
    path. Duplicate coordinates sum. Emits one ``ingest.sort`` event
    per call (rows/nnz/shards/wall_ms and which route ran); the mesh
    route's collective volume additionally lands in the measured-comm
    ``comm.sort`` events its SiteLedgers commit.
    """
    from ..config import settings
    from ..parallel.mesh import get_mesh
    from ..parallel.sort import coo_to_csr_distributed

    rows = np.asarray(rows).reshape(-1)
    cols = np.asarray(cols).reshape(-1)
    vals = np.asarray(vals).reshape(-1)
    if not (rows.shape[0] == cols.shape[0] == vals.shape[0]):
        raise ValueError(
            f"COO arrays disagree: {rows.shape[0]} rows, "
            f"{cols.shape[0]} cols, {vals.shape[0]} vals"
        )
    m, n = int(shape[0]), int(shape[1])
    if settings.force_serial:
        num_shards = 1
    S = int(get_mesh(num_shards).devices.size)
    t0 = time.monotonic()
    fast = S == 1 and m * n <= np.iinfo(np.int32).max
    if fast:
        srows, scols, svals = _sort_single_device(rows, cols, vals, shape)
        out = _dedup_sorted(srows, scols, svals, (m, n))
    else:
        out = coo_to_csr_distributed(rows, cols, vals, (m, n), S)
    if telemetry.enabled():
        telemetry.record(
            "ingest.sort", rows=m, nnz=int(out.nnz), shards=S,
            entries=int(rows.shape[0]), fast_path=bool(fast),
            wall_ms=round((time.monotonic() - t0) * 1e3, 3),
        )
    return out


def balance(indptr, num_shards: int) -> np.ndarray:
    """nnz-balanced row partition: S+1 monotone row boundaries so each
    shard's ``[bounds[s], bounds[s+1])`` row slab carries ~``nnz/S``
    nonzeros — the reference's ``balance()`` (SURVEY §2c-3), which
    re-splits by prefix-nnz instead of row count so skewed arrivals
    (a few dense rows) don't serialize on one shard."""
    indptr = np.asarray(indptr, dtype=np.int64).reshape(-1)
    S = int(num_shards)
    if S < 1:
        raise ValueError(f"num_shards must be >= 1, got {num_shards}")
    m = indptr.shape[0] - 1
    if m < 0:
        raise ValueError("indptr must have at least one entry")
    nnz = int(indptr[-1])
    targets = (np.arange(1, S, dtype=np.float64) * nnz) / S
    cuts = np.searchsorted(indptr, targets, side="left")
    bounds = np.concatenate([[0], cuts, [m]]).astype(np.int64)
    # monotone + in-range even for degenerate inputs (nnz=0, S > m)
    return np.maximum.accumulate(np.clip(bounds, 0, m))


def balance_stats(indptr, num_shards: int) -> dict:
    """How much :func:`balance` helps THIS row profile: per-shard nnz
    under the balanced partition vs the uniform ``m/S`` row split, and
    their imbalance ratios (max shard nnz / mean — 1.0 is perfect)."""
    indptr = np.asarray(indptr, dtype=np.int64).reshape(-1)
    S = int(num_shards)
    m = indptr.shape[0] - 1
    nnz = int(indptr[-1])
    bal = balance(indptr, S)
    bal_nnz = np.diff(indptr[bal])
    uni = np.clip(
        np.round(np.arange(S + 1) * m / S).astype(np.int64), 0, m
    )
    uni_nnz = np.diff(indptr[uni])
    mean = max(nnz / S, 1e-12)
    return {
        "shards": S,
        "rows": m,
        "nnz": nnz,
        "bounds": bal.tolist(),
        "balanced_nnz": bal_nnz.tolist(),
        "uniform_nnz": uni_nnz.tolist(),
        "balanced_imbalance": float(bal_nnz.max() / mean) if S else 1.0,
        "uniform_imbalance": float(uni_nnz.max() / mean) if S else 1.0,
    }
