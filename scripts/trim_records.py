#!/usr/bin/env python
"""Cap the committed session logs (results/axon/records*.jsonl) to the
latest bench session, so telemetry evidence doesn't grow the repo
unboundedly (ISSUE 2 CI/tooling satellite).

Kept lines:
  * everything belonging to the LATEST session window — from the last
    ``bench.session`` record's run start (its ts minus budget_spent_s,
    with slack) onward;
  * the freshest ``_tpu`` hardware metric record regardless of age —
    bench.py's wedged-tunnel fallback (``_freshest_session_record``)
    must never lose its only hardware evidence to a trim;
  * the latest ``session.start`` record regardless of age — without its
    epoch/monotonic clock base a per-process file can no longer be
    clock-aligned by ``scripts/axon_merge.py`` (ISSUE 7 satellite).

Under multi-controller the sink splits into ``records.<pid>.jsonl``
per-process files; the CLI globs and trims each one (a per-process file
without a ``bench.session`` record is kept whole — the window anchor
lives in the controller-0 log). Run from anywhere:
``python scripts/trim_records.py [--dry-run]``. CI/round tooling runs
it before committing results.

Incident bundles (ISSUE 12 satellite): the flight recorder writes
postmortem bundle DIRECTORIES under ``results/axon/incidents/``; the
same bounded-retention policy as the vault quarantine applies — the
newest ``KEEP_INCIDENTS`` bundles are kept, older ones removed — so
committed results stay small even after an alert storm.

History segments (Axon v7 satellite): the continuous-telemetry sampler
writes append-only ``seg-*.jsonl`` segments under
``results/axon/history/``; ``trim_history`` keeps only the NEWEST
session's segments (the sampler's own byte-cap GC bounds a live
session; this bounds what survives across sessions into a commit) and
empties the ``quarantine/`` subdirectory of corrupt segments.
"""

import glob as _glob
import json
import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
AXON_DIR = os.path.join(HERE, "..", "results", "axon")
RECORDS = os.path.join(AXON_DIR, "records.jsonl")
INCIDENTS_DIR = os.path.join(AXON_DIR, "incidents")
HISTORY_DIR = os.path.join(AXON_DIR, "history")
SLACK_S = 120.0  # clock slack around the session window
KEEP_INCIDENTS = 4  # newest bundles kept by trim_incidents


def _roundtrip_ok(kept, original) -> bool:
    """The trimmed log must still round-trip through the schema
    validator and the Chrome-trace exporter (line renumbering and
    partial sessions are exactly where a naive trim corrupts the log).

    Trimming only removes whole lines, so the kept lines' schema
    problems must be a subset of the original's (a pre-existing bad
    line that survives the window is evidence, not a trim failure) and
    ``export_trace``'s builder must accept the kept events. Returns
    False — caller aborts the rewrite — on any new problem. Skipped
    (True, with a note) when sparse_tpu isn't importable."""
    try:
        sys.path.insert(0, REPO)
        from sparse_tpu.telemetry import _schema, _trace
    except Exception as e:  # no jax in this interpreter: don't block a trim
        print(f"trim_records: round-trip check skipped ({e!r})")
        return True

    def problems_by_line(lines):
        bad = {}
        for ln in lines:
            try:
                ev = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if not isinstance(ev, dict) or "kind" not in ev:
                continue  # bench metric record: not a telemetry event
            probs = _schema.validate(ev)
            if probs:
                bad[ln] = tuple(probs)
        return bad

    orig_bad = problems_by_line(original)
    new_bad = {
        ln: p for ln, p in problems_by_line(kept).items()
        if ln not in orig_bad
    }
    if new_bad:
        print(
            f"trim_records: ABORT — trim would introduce {len(new_bad)} "
            "schema problem(s) the original log did not have"
        )
        return False
    try:
        events = []
        for ln in kept:
            try:
                ev = json.loads(ln)
            except json.JSONDecodeError:
                continue
            if isinstance(ev, dict) and "kind" in ev:
                events.append(ev)
        trace = _trace.to_chrome_trace(events)
        if "traceEvents" not in trace:
            raise ValueError("no traceEvents in export")
    except Exception as e:
        print(f"trim_records: ABORT — trimmed log fails trace export ({e!r})")
        return False
    return True


def trim(path: str = RECORDS, dry_run: bool = False) -> int:
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        print(f"trim_records: no session log at {os.path.basename(path)}")
        return 0

    parsed = []
    for ln in lines:
        try:
            parsed.append((ln, json.loads(ln)))
        except json.JSONDecodeError:
            parsed.append((ln, None))  # keep unparseable lines (evidence)

    sessions = [
        r for _, r in parsed
        if isinstance(r, dict) and r.get("kind") == "bench.session"
        and isinstance(r.get("ts"), (int, float))
    ]
    if not sessions:
        print("trim_records: no bench.session record; keeping everything")
        return 0
    last = max(sessions, key=lambda r: r["ts"])
    start = last["ts"] - float(last.get("budget_spent_s", 0.0)) - SLACK_S

    freshest_line = None
    best_ts = None
    session_line = None  # latest session.start: the merge clock base
    session_ts = None
    for ln, r in parsed:
        if (
            isinstance(r, dict)
            and isinstance(r.get("metric"), str)
            and "_tpu" in r["metric"]
            and isinstance(r.get("ts"), (int, float))
        ):
            if best_ts is None or r["ts"] > best_ts:
                best_ts, freshest_line = r["ts"], ln
        if (
            isinstance(r, dict)
            and r.get("kind") == "session.start"
            and isinstance(r.get("ts"), (int, float))
        ):
            if session_ts is None or r["ts"] > session_ts:
                session_ts, session_line = r["ts"], ln

    kept = []
    for ln, r in parsed:
        ts = r.get("ts") if isinstance(r, dict) else None
        in_window = isinstance(ts, (int, float)) and ts >= start
        if in_window or r is None or ln in (freshest_line, session_line):
            kept.append(ln)

    dropped = len(lines) - len(kept)
    print(
        f"trim_records: {os.path.basename(path)}: "
        f"{len(lines)} lines -> {len(kept)} "
        f"(dropped {dropped}; window starts {start:.0f})"
    )
    if dropped and not dry_run:
        if not _roundtrip_ok(kept, lines):
            return 0  # keep the original log untouched
        # the log's directory can be absent in a fresh checkout that
        # never ran bench (results/axon is created lazily by the sink)
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        with open(path, "w") as f:
            f.write("\n".join(kept) + "\n")
    return dropped


def trim_incidents(root: str = INCIDENTS_DIR, keep: int = KEEP_INCIDENTS,
                   dry_run: bool = False) -> int:
    """Prune the incident-bundle directory to the newest ``keep``
    bundles (ISSUE 12 satellite). A bundle is any subdirectory holding
    an ``incident.json`` manifest; names carry a timestamp prefix, so a
    name sort IS a chronological sort. Non-bundle entries (stray files,
    a manifest-less dir) are left alone — this prunes only what the
    flight recorder wrote. Returns the number of bundles removed."""
    try:
        names = sorted(
            n for n in os.listdir(root)
            if os.path.isfile(os.path.join(root, n, "incident.json"))
        )
    except OSError:
        print("trim_records: no incident bundles; nothing to do")
        return 0
    doomed = names[: max(len(names) - max(int(keep), 0), 0)]
    print(
        f"trim_records: incidents: {len(names)} bundle(s) -> "
        f"{len(names) - len(doomed)} (removing {len(doomed)}, keep "
        f"newest {keep})"
    )
    if dry_run:
        return len(doomed)
    removed = 0
    for n in doomed:
        try:
            shutil.rmtree(os.path.join(root, n))
            removed += 1
        except OSError as e:
            print(f"trim_records: could not remove incidents/{n}: {e}")
    return removed


def trim_history(root: str = HISTORY_DIR, dry_run: bool = False) -> int:
    """Keep only the newest session's history segments (Axon v7
    satellite). Segment names (``seg-<epoch_ms>-<seq>.jsonl``) sort
    chronologically; the session owning the newest segment survives,
    every older session's segments go, and quarantined corrupt segments
    (``quarantine/``) are emptied. The live sampler's byte-cap GC
    bounds a running session — this bounds the committed residue.
    Returns the number of files removed."""

    def _session_of(name):
        try:
            with open(os.path.join(root, name)) as f:
                head = json.loads(f.readline())
            if head.get("kind") == "history.segment":
                return head.get("session")
        except (OSError, json.JSONDecodeError, ValueError):
            pass
        return None

    try:
        names = sorted(
            n for n in os.listdir(root)
            if n.startswith("seg-") and n.endswith(".jsonl")
        )
    except OSError:
        print("trim_records: no history segments; nothing to do")
        return 0
    quarantined = sorted(_glob.glob(os.path.join(root, "quarantine", "*")))
    keep_session = _session_of(names[-1]) if names else None
    doomed = [
        n for n in names
        if keep_session is None or _session_of(n) != keep_session
    ]
    print(
        f"trim_records: history: {len(names)} segment(s) -> "
        f"{len(names) - len(doomed)} (removing {len(doomed)} from older "
        f"sessions, {len(quarantined)} quarantined)"
    )
    if dry_run:
        return len(doomed) + len(quarantined)
    removed = 0
    for path in [os.path.join(root, n) for n in doomed] + quarantined:
        try:
            os.remove(path)
            removed += 1
        except OSError as e:
            print(f"trim_records: could not remove {path}: {e}")
    return removed


def trim_all(dry_run: bool = False) -> int:
    """Trim every committed session log — the single-controller
    ``records.jsonl`` plus any per-process ``records.<pid>.jsonl`` the
    multi-controller sink split produced. Merge outputs
    (``records.merged.jsonl``) are trimmed like any other log. Incident
    bundles are pruned to the newest ``KEEP_INCIDENTS`` and history
    segments to the newest session alongside."""
    paths = sorted(_glob.glob(os.path.join(AXON_DIR, "records*.jsonl")))
    if not paths:
        print("trim_records: no session logs; nothing to do")
        dropped = 0
    else:
        dropped = sum(trim(p, dry_run=dry_run) for p in paths)
    return (dropped + trim_incidents(dry_run=dry_run)
            + trim_history(dry_run=dry_run))


if __name__ == "__main__":
    trim_all(dry_run="--dry-run" in sys.argv)
