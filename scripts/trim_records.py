#!/usr/bin/env python
"""Cap the committed session log (results/axon/records.jsonl) to the
latest bench session, so telemetry evidence doesn't grow the repo
unboundedly (ISSUE 2 CI/tooling satellite).

Kept lines:
  * everything belonging to the LATEST session window — from the last
    ``bench.session`` record's run start (its ts minus budget_spent_s,
    with slack) onward;
  * the freshest ``_tpu`` hardware metric record regardless of age —
    bench.py's wedged-tunnel fallback (``_freshest_session_record``)
    must never lose its only hardware evidence to a trim.

Run from anywhere: ``python scripts/trim_records.py [--dry-run]``.
CI/round tooling runs it before committing results.
"""

import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
RECORDS = os.path.join(HERE, "..", "results", "axon", "records.jsonl")
SLACK_S = 120.0  # clock slack around the session window


def trim(path: str = RECORDS, dry_run: bool = False) -> int:
    try:
        with open(path) as f:
            lines = [ln for ln in f.read().splitlines() if ln.strip()]
    except OSError:
        print("trim_records: no session log; nothing to do")
        return 0

    parsed = []
    for ln in lines:
        try:
            parsed.append((ln, json.loads(ln)))
        except json.JSONDecodeError:
            parsed.append((ln, None))  # keep unparseable lines (evidence)

    sessions = [
        r for _, r in parsed
        if isinstance(r, dict) and r.get("kind") == "bench.session"
        and isinstance(r.get("ts"), (int, float))
    ]
    if not sessions:
        print("trim_records: no bench.session record; keeping everything")
        return 0
    last = max(sessions, key=lambda r: r["ts"])
    start = last["ts"] - float(last.get("budget_spent_s", 0.0)) - SLACK_S

    freshest_line = None
    best_ts = None
    for ln, r in parsed:
        if (
            isinstance(r, dict)
            and isinstance(r.get("metric"), str)
            and "_tpu" in r["metric"]
            and isinstance(r.get("ts"), (int, float))
        ):
            if best_ts is None or r["ts"] > best_ts:
                best_ts, freshest_line = r["ts"], ln

    kept = []
    for ln, r in parsed:
        ts = r.get("ts") if isinstance(r, dict) else None
        in_window = isinstance(ts, (int, float)) and ts >= start
        if in_window or r is None or ln == freshest_line:
            kept.append(ln)

    dropped = len(lines) - len(kept)
    print(
        f"trim_records: {len(lines)} lines -> {len(kept)} "
        f"(dropped {dropped}; window starts {start:.0f})"
    )
    if dropped and not dry_run:
        with open(path, "w") as f:
            f.write("\n".join(kept) + "\n")
    return dropped


if __name__ == "__main__":
    trim(dry_run="--dry-run" in sys.argv)
