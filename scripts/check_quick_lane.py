"""Quick-lane integrity guard (ISSUE 3 satellite).

The quick lane (`pytest tests/ -m quick`, conftest._QUICK_FILES) is the
builder inner loop; it regresses silently in two ways: a listed file is
deleted/renamed (the marker hook simply stops matching — nothing fails),
or a refactor quietly drops tests from a quick module. This script fails
on both, against a committed manifest:

* every file in ``tests/conftest.py::_QUICK_FILES`` must exist;
* every manifest entry must still be in ``_QUICK_FILES`` (and vice
  versa — a new quick file must be manifested);
* each file's statically-collected test count (``test_*`` functions at
  module scope and inside ``Test*`` classes, counted by ``ast`` — no
  imports, no jax init, so the check costs milliseconds) must not DROP
  below the manifest; growth is fine and prompts a friendly note.

Usage:
    python scripts/check_quick_lane.py            # check, exit 1 on problems
    python scripts/check_quick_lane.py --update   # regenerate the manifest

Wired into the suite by ``tests/test_quick_lane.py`` (itself in the
quick lane) so tier-1 catches lane regressions.
"""

from __future__ import annotations

import ast
import json
import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
TESTS = os.path.join(REPO, "tests")
CONFTEST = os.path.join(TESTS, "conftest.py")
MANIFEST = os.path.join(TESTS, "quick_lane_manifest.json")

# CLI tooling the quick lane exercises (tests/test_axon_report.py loads
# these by path): a rename/deletion must fail here, not at collection
# time inside an importlib call with a cryptic spec error.
_REQUIRED_SCRIPTS = (
    "axon_dash.py",
    "axon_doctor.py",
    "axon_merge.py",
    "axon_report.py",
    "axon_serve.py",
    "axon_trace.py",
    "chaos_check.py",
    "check_quick_lane.py",
    "trim_records.py",
    "vault_gc.py",
)


def quick_files() -> set:
    """The ``_QUICK_FILES`` set, read by ast (importing conftest mutates
    the process env and initializes jax — far too heavy for a guard)."""
    tree = ast.parse(open(CONFTEST).read(), filename=CONFTEST)
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            for t in node.targets:
                if isinstance(t, ast.Name) and t.id == "_QUICK_FILES":
                    return set(ast.literal_eval(node.value))
    raise RuntimeError(f"_QUICK_FILES not found in {CONFTEST}")


def count_tests(path: str) -> int:
    """Static test count: ``test_*`` defs at module scope plus methods of
    ``Test*`` classes (pytest's default collection surface). Parametrize
    multiplies runtime counts, but a *static* drop is exactly the
    silent-deletion signal this guard exists for."""
    tree = ast.parse(open(path).read(), filename=path)
    n = 0
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name.startswith("test"):
                n += 1
        elif isinstance(node, ast.ClassDef) and node.name.startswith("Test"):
            for sub in node.body:
                if isinstance(
                    sub, (ast.FunctionDef, ast.AsyncFunctionDef)
                ) and sub.name.startswith("test"):
                    n += 1
    return n


def current_counts() -> dict:
    return {
        f: count_tests(os.path.join(TESTS, f)) for f in sorted(quick_files())
        if os.path.exists(os.path.join(TESTS, f))
    }


def check_scripts() -> list:
    """Tooling integrity: every required script exists and parses
    (pure-ast, same zero-import discipline as the test counter)."""
    problems = []
    for name in _REQUIRED_SCRIPTS:
        path = os.path.join(HERE, name)
        if not os.path.exists(path):
            problems.append(
                f"scripts/{name} is required by the quick lane but missing "
                "(renamed without updating check_quick_lane._REQUIRED_SCRIPTS?)"
            )
            continue
        try:
            ast.parse(open(path).read(), filename=path)
        except SyntaxError as e:
            problems.append(f"scripts/{name} does not parse: {e}")
    return problems


def check() -> list:
    """Returns a list of problem strings (empty = lane intact)."""
    problems = check_scripts()
    files = quick_files()
    for f in sorted(files):
        if not os.path.exists(os.path.join(TESTS, f)):
            problems.append(
                f"quick-lane file missing: tests/{f} is in _QUICK_FILES "
                "but does not exist (renamed without updating conftest?)"
            )
    if not os.path.exists(MANIFEST):
        problems.append(
            f"manifest missing: {os.path.relpath(MANIFEST, REPO)} "
            "(run scripts/check_quick_lane.py --update)"
        )
        return problems
    manifest = json.load(open(MANIFEST))
    recorded = manifest.get("files", {})
    for f in sorted(set(recorded) - files):
        problems.append(
            f"tests/{f} is in the manifest but no longer in _QUICK_FILES "
            "(lane shrank; update the manifest deliberately if intended)"
        )
    for f in sorted(files - set(recorded)):
        problems.append(
            f"tests/{f} joined _QUICK_FILES but is not manifested "
            "(run scripts/check_quick_lane.py --update)"
        )
    for f, have in current_counts().items():
        want = recorded.get(f)
        if want is not None and have < want:
            problems.append(
                f"tests/{f}: {have} collected tests < manifest {want} "
                "(tests dropped from the quick lane)"
            )
    total_want = manifest.get("total", 0)
    total_have = sum(current_counts().values())
    if total_have < total_want:
        problems.append(
            f"quick-lane total {total_have} < manifest total {total_want}"
        )
    return problems


def update() -> dict:
    counts = current_counts()
    manifest = {
        "_comment": (
            "Committed quick-lane floor (scripts/check_quick_lane.py): "
            "static per-file test counts; counts may grow freely, a drop "
            "fails tests/test_quick_lane.py. Regenerate with --update."
        ),
        "files": counts,
        "total": sum(counts.values()),
    }
    with open(MANIFEST, "w") as f:
        json.dump(manifest, f, indent=1, sort_keys=True)
        f.write("\n")
    return manifest


def main(argv) -> int:
    if "--update" in argv:
        m = update()
        print(
            f"manifest updated: {len(m['files'])} files, "
            f"{m['total']} tests -> {os.path.relpath(MANIFEST, REPO)}"
        )
        return 0
    problems = check()
    for p in problems:
        print(f"QUICK-LANE REGRESSION: {p}", file=sys.stderr)
    if not problems:
        counts = current_counts()
        print(
            f"quick lane intact: {len(counts)} files, "
            f"{sum(counts.values())} tests (>= manifest)"
        )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
