"""GMG grid-pipeline vs generic-hierarchy parity matrix (VERDICT r4 #9).

Runs ``examples/gmg.py`` both ways — the structured-grid stencil pipeline
(``models/gmg_grid.py``, the default) and ``--no-grid`` (the generic
sparse-matrix hierarchy) — across a {n, levels, gridop} matrix on the CPU
backend, and compares:

- **iterations**: must MATCH, AND **residuals must agree** to 1% — for
  runs that hit the -maxiter cap the iteration count alone is vacuous,
  but an identical residual after the same number of iterations pins the
  whole CG trajectory (the stronger iterate-parity statement; small-n
  exact-iterate oracle in tests/test_gmg_grid.py);
- **init/solve speedup**: the CPU-measurable part of the r4 claim that the
  grid pipeline is ~3x faster, so the first live TPU window only needs to
  measure, not debug.

Writes ``results/gmg_parity_matrix.json`` and prints a table. Pure-CPU by
construction (the tunnel is never touched).

Run:  python scripts/gmg_parity_matrix.py [-quick]
"""

import argparse
import json
import os
import re
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


MAXITER = 100


def run_one(n, levels, gridop, no_grid, maxiter=MAXITER):
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    cmd = [
        sys.executable, os.path.join(REPO, "examples", "gmg.py"),
        "-n", str(n), "-levels", str(levels), "-gridop", gridop,
        "-maxiter", str(maxiter),
    ]
    if no_grid:
        cmd.append("--no-grid")
    proc = subprocess.run(
        cmd, capture_output=True, text=True, timeout=1800, env=env, cwd=REPO
    )
    out = proc.stdout
    m_it = re.search(r"Iterations:\s+(\d+)\s+residual:\s+([0-9.e+-]+)", out)
    m_init = re.search(r"GMG init time:\s+([0-9.]+)\s+ms", out)
    m_solve = re.search(r"Solve time:\s+([0-9.]+)\s+ms", out)
    if not (m_it and m_init and m_solve):
        raise RuntimeError(
            f"unparseable gmg.py output (rc={proc.returncode}):\n"
            f"{out[-800:]}\n{proc.stderr[-800:]}"
        )
    return {
        "iters": int(m_it.group(1)),
        "residual": float(m_it.group(2)),
        "init_ms": float(m_init.group(1)),
        "solve_ms": float(m_solve.group(1)),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("-quick", action="store_true", help="small-n subset")
    args = ap.parse_args()

    if args.quick:
        configs = [(128, 3, "linear"), (128, 3, "injection")]
    else:
        configs = [
            (n, lv, op)
            for n in (128, 256)
            for lv in (3, 5)
            for op in ("linear", "injection")
        ] + [(512, 5, "linear")]

    rows = []
    ok = True
    for n, lv, op in configs:
        grid = run_one(n, lv, op, no_grid=False)
        gen = run_one(n, lv, op, no_grid=True)
        iters_match = grid["iters"] == gen["iters"]
        resid_rel = abs(grid["residual"] - gen["residual"]) / max(
            abs(gen["residual"]), 1e-30
        )
        converged = grid["iters"] < MAXITER and gen["iters"] < MAXITER
        # capped rows: the residual IS the parity evidence (same count is
        # vacuous at the cap) — require near-exact agreement (observed
        # Δ0.0). Converged rows: both residuals sit at ~tol*||b|| where a
        # few percent of relative difference is FP noise between the
        # stencil and CSR formulations of the same tiny number; iteration
        # match is the parity statement, 5% residual agreement the sanity
        # bound.
        resid_match = resid_rel < (0.05 if converged else 1e-2)
        row_ok = iters_match and resid_match
        ok = ok and row_ok
        row = {
            "n": n, "levels": lv, "gridop": op,
            "iters_grid": grid["iters"], "iters_generic": gen["iters"],
            "iters_match": iters_match,
            "residual_grid": grid["residual"],
            "residual_generic": gen["residual"],
            "residual_rel_diff": float(f"{resid_rel:.2e}"),
            "residual_match": resid_match,
            "init_speedup": round(gen["init_ms"] / max(grid["init_ms"], 1e-9), 2),
            "solve_speedup": round(
                gen["solve_ms"] / max(grid["solve_ms"], 1e-9), 2
            ),
        }
        rows.append(row)
        print(
            f"n={n:4d} L={lv} {op:9s}  iters {grid['iters']:3d}"
            f"{'==' if iters_match else '!='}{gen['iters']:<3d}"
            f" resid Δ{resid_rel:.1e}{'ok' if resid_match else ' MISMATCH'}"
            f"  init x{row['init_speedup']:<6}  solve x{row['solve_speedup']}"
        )

    artifact = {"parity_ok": ok, "quick": bool(args.quick),
                "configs": [list(c) for c in configs], "rows": rows}
    os.makedirs(os.path.join(REPO, "results"), exist_ok=True)
    # quick smoke runs must not clobber the committed full-matrix evidence
    name = ("gmg_parity_matrix_quick.json" if args.quick
            else "gmg_parity_matrix.json")
    path = os.path.join(REPO, "results", name)
    with open(path, "w") as f:
        json.dump(artifact, f, indent=1)
    print(f"parity_ok={ok}  -> {path}")
    sys.exit(0 if ok else 1)


if __name__ == "__main__":
    main()
