"""One-command hardware-evidence capture for a live tunnel window.

Four rounds produced zero driver-visible TPU lines because every live
window was spent choosing what to run. This script IS the choice: the
full queued evidence list (BENCH_NOTES r4 items 1-7, VERDICT r4 next-round
1-4), serialized through ONE client, probe-gated between steps, each
step's verbatim stdout banked to ``results/axon/`` the moment it exists
(the reference's results/summit/*.out discipline).

Order is cheap -> impressive so a short window still banks something:
  1. bench.py full flow (headline fused-CG 6000^2, SpMV+tile autotune,
     SpMM, GMG grid-pipeline, AMG, quantum rows; logs its own records)
  2. public-API PDE 6000^2 throughput (examples/pde.py)
  3. GMG grid pipeline n=2000 -> 4000 -> 4500 (the reference's exact shape)
  4. AMG n=512 example run
  5. c64 hardware lane (RUN_TPU_HW pytest + tpu_complex_check)
  6. SpGEMM microbenchmark
  7. quantum evolution >=1e5 states

A step timeout or failed probe STOPS the run (a wedged tunnel must not be
hammered; memory: probes every ~15-20 min, one client only).

Run:  python scripts/hw_window.py [--budget 7200] [--from N]
"""

import argparse
import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from bench import _log_hw_text, _probe_tpu  # noqa: E402

STEPS = [
    # (name, timeout_s, argv, extra_env)
    ("bench_full", 2700, [sys.executable, "bench.py"],
     {"BENCH_BUDGET_S": "2400"}),
    ("pde_public_6000", 900,
     [sys.executable, "examples/pde.py", "-throughput", "-max_iter", "300",
      "-nx", "6000", "-ny", "6000", "--precision", "f32"], {}),
    ("gmg_grid_2000", 900,
     [sys.executable, "examples/gmg.py", "-n", "2000", "-maxiter", "300",
      "--precision", "f32"], {}),
    ("gmg_grid_4000", 1200,
     [sys.executable, "examples/gmg.py", "-n", "4000", "-maxiter", "300",
      "--precision", "f32"], {}),
    ("gmg_grid_4500", 1500,
     [sys.executable, "examples/gmg.py", "-n", "4500", "-maxiter", "300",
      "--precision", "f32"], {}),
    ("amg_512", 1200,
     [sys.executable, "examples/amg.py", "-n", "512", "--precision", "f32"],
     {}),
    ("c64_lane", 900,
     [sys.executable, "-m", "pytest", "tests/test_complex_stacked.py", "-q"],
     {"RUN_TPU_HW": "1"}),
    ("c64_check", 600,
     [sys.executable, "scripts/tpu_complex_check.py"], {}),
    ("spgemm_micro", 900,
     [sys.executable, "examples/spgemm_microbenchmark.py"], {}),
    ("dot_micro_10m", 900,
     [sys.executable, "examples/dot_microbenchmark.py", "-n", "10000000",
      "-i", "200", "--precision", "f32"], {}),
    ("quantum_cycle25", 1200,
     [sys.executable, "examples/quantum_evolution.py", "-graph", "cycle",
      "-nodes", "25", "-t", "0.05", "--precision", "f32"], {}),
]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--budget", type=float, default=7200.0)
    ap.add_argument("--from", dest="start", type=int, default=0,
                    help="resume from step index N")
    args = ap.parse_args()
    t0 = time.monotonic()

    def remaining():
        return args.budget - (time.monotonic() - t0)

    results = []
    for idx, (name, step_to, argv, extra) in enumerate(STEPS):
        if idx < args.start:
            continue
        if remaining() < 180:
            print(f"hw_window: out of budget before {name}", flush=True)
            break
        status = _probe_tpu(min(150, remaining() - 30))
        if status != "tpu":
            print(f"hw_window: probe says '{status}' before {name}; STOP "
                  f"(resume later with --from {idx})", flush=True)
            break
        env = dict(os.environ)
        env.update(extra)
        eff_to = min(step_to, max(remaining() - 30, 60))
        budget_truncated = eff_to < step_to
        print(f"hw_window: [{idx}] {name} (timeout {eff_to:.0f}s"
              f"{', budget-truncated' if budget_truncated else ''})",
              flush=True)
        t1 = time.perf_counter()
        # Popen + drain-after-kill rather than subprocess.run: run()'s
        # TimeoutExpired carries only the bytes read up to the TIMEOUT;
        # the explicit kill-then-drain also collects whatever the child
        # wrote between the timeout and the kill, and hands back str not
        # bytes. A partial GMG log still carries init/iteration evidence.
        proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=REPO, env=env,
        )
        try:
            out, err = proc.communicate(timeout=eff_to)
            wall = time.perf_counter() - t1
            _log_hw_text(name, out + "\n--- stderr ---\n" + err[-4000:])
            row = {"step": name, "rc": proc.returncode,
                   "wall_s": round(wall, 1)}
            print(json.dumps(row), flush=True)
            for ln in out.strip().splitlines()[-8:]:
                print(f"    {ln}", flush=True)
            results.append(row)
        except subprocess.TimeoutExpired as outer:
            proc.kill()

            def _txt(v):  # TimeoutExpired attrs are bytes even w/ text=True
                if isinstance(v, bytes):
                    return v.decode(errors="replace")
                return v or ""

            try:  # drain what the child printed before the kill
                partial, perr = proc.communicate(timeout=30)
            except subprocess.TimeoutExpired as inner:
                # a grandchild still holds the pipes: salvage what the
                # drain read before giving up on it
                partial, perr = _txt(inner.stdout), _txt(inner.stderr)
            if not partial:
                partial = _txt(outer.stdout)  # pre-timeout reads, if any
            if not perr:
                perr = _txt(outer.stderr)
            _log_hw_text(
                name,
                f"{partial}\n--- stderr ---\n{perr[-4000:]}\n"
                f"--- TIMEOUT after {eff_to:.0f}s"
                f"{' (budget-truncated, NOT a wedge verdict)' if budget_truncated else ''} ---",
            )
            if budget_truncated:
                # killed by OUR budget, not the tunnel: the step is
                # unfinished, resume must re-run it
                print(f"hw_window: {name} hit the remaining-budget clamp "
                      f"({eff_to:.0f}s < {step_to}s); resume with "
                      f"--from {idx}", flush=True)
            else:
                print(f"hw_window: {name} TIMED OUT at its full {step_to}s "
                      f"— wedge signature, STOP (resume later with "
                      f"--from {idx})", flush=True)
            results.append({"step": name, "rc": None, "timeout": True,
                            "budget_truncated": budget_truncated})
            break
    print(json.dumps({"hw_window": results}), flush=True)


if __name__ == "__main__":
    main()
