"""TPU kernel measurement sweep (developer tool).

Runs the kernel-level comparisons that guided the Pallas work, one guarded
step at a time, printing a JSON line per measurement immediately (the
remote tunnel can die mid-run; everything printed so far survives).

Usage:  python scripts/tpu_measure.py [--sizes 2000,6000] [--skip-cg]
Timing fences on host scalar fetches with chain-slope correction
(see bench._time_kernel) — block_until_ready does not fence the tunnel.
"""

import argparse
import json
import os
import sys
import time
import traceback

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from bench import _time_kernel  # noqa: E402
from sparse_tpu.utils import enable_compilation_cache  # noqa: E402

enable_compilation_cache()


def emit(name, **kw):
    print(json.dumps({"step": name, **kw}), flush=True)


def guarded(name):
    def deco(fn):
        def run(*a, **kw):
            try:
                t0 = time.perf_counter()
                out = fn(*a, **kw)
                emit(name, ok=True, wall_s=round(time.perf_counter() - t0, 1), **(out or {}))
                return out
            except Exception as e:
                traceback.print_exc(file=sys.stderr)
                emit(name, ok=False, error=str(e)[:200])
                return None

        return run

    return deco


@guarded("devices")
def step_devices():
    import jax

    d = jax.devices()[0]
    return {"kind": getattr(d, "device_kind", "?"), "platform": d.platform}


@guarded("dia_spmv_compare")
def step_dia_compare(n):
    """v1 (per-call repack) vs packed v2 DIA SpMV on the n^2 Laplacian."""
    import jax.numpy as jnp

    from sparse_tpu.kernels.dia_spmv import PreparedDia, dia_spmv_pallas
    from sparse_tpu.models.poisson import laplacian_2d_dia
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    x = jnp.ones((N,), jnp.float32)
    nnz = 5 * N
    out = {}
    for name, step in (
        ("xla", lambda xx: dia_spmv_xla(planes, offsets, xx, (N, N))),
        ("pallas_v1", lambda xx: dia_spmv_pallas(planes, offsets, xx, (N, N))),
        ("pallas_packed", PreparedDia(planes, offsets, (N, N))),
        ("pallas_packed_t16k", PreparedDia(planes, offsets, (N, N), tile=16384)),
    ):
        try:
            s = _time_kernel(step, x)
            out[name] = {"ms": round(s * 1e3, 3), "gflops": round(2 * nnz / s / 1e9, 1)}
        except Exception as e:
            traceback.print_exc(file=sys.stderr)
            out[name] = {"error": str(e)[:150]}
    return {"n": n, **out}


@guarded("spmv_11diag")
def step_11diag(rows=10_000_000):
    from bench import SPMV_BASELINE_ITERS_PER_S, run_spmv_11diag

    v, tile, band = run_spmv_11diag(rows)
    return {
        "rows": rows,
        "iters_per_s": round(v, 1),
        "vs_v100": round(v / SPMV_BASELINE_ITERS_PER_S, 2),
        "tile": tile,
        "tile_band_us": {str(t): round(s * 1e6, 1) for t, s in band.items()},
    }


@guarded("cg_variants")
def step_cg(n, iters=300):
    import jax
    import jax.numpy as jnp

    from sparse_tpu.kernels.cg_dia import cg_dia_fused, cg_dia_fused_onepass
    from sparse_tpu.models.poisson import laplacian_2d_dia, cg_dia, poisson_cg_state_dia
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    b = dia_spmv_xla(planes, offsets,
                     jax.random.normal(jax.random.PRNGKey(0), (N,), jnp.float32),
                     (N, N))
    out = {"n": n}

    state, stepfn = poisson_cg_state_dia(n)
    o = cg_dia(stepfn, *state, iters=iters)
    float(o[-1])
    best = 0.0
    for _ in range(2):
        t0 = time.perf_counter()
        o = cg_dia(stepfn, *state, iters=iters)
        float(o[-1])
        best = max(best, iters / (time.perf_counter() - t0))
    out["step_loop"] = round(best, 1)

    for fn, name in ((cg_dia_fused, "twopass"), (cg_dia_fused_onepass, "onepass")):
        for tile in (16384, 65536):
            key = f"{name}_t{tile // 1024}k"
            try:
                o = fn(planes, offsets, b, None, N, iters=iters, tile=tile)
                rho = float(o[2])
                best = 0.0
                for _ in range(2):
                    t0 = time.perf_counter()
                    o = fn(planes, offsets, b, None, N, iters=iters, tile=tile)
                    float(o[2])
                    best = max(best, iters / (time.perf_counter() - t0))
                out[key] = {"iters_per_s": round(best, 1), "rho": float(f"{rho:.3e}")}
            except Exception as e:
                traceback.print_exc(file=sys.stderr)
                out[key] = {"error": str(e)[:150]}
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="2000,6000")
    ap.add_argument("--skip-cg", action="store_true")
    args = ap.parse_args()
    sizes = [int(s) for s in args.sizes.split(",")]

    if not step_devices():
        sys.exit(1)
    step_dia_compare(sizes[0])
    step_11diag()
    if not args.skip_cg:
        for n in sizes[1:] or sizes[:1]:
            step_cg(n)


if __name__ == "__main__":
    main()
