"""On-device complex64 lane: stacked-real transfers, native c64 compute.

Runs on the DEFAULT backend (the axon TPU tunnel under the harness env;
CPU elsewhere) and exercises the public API end to end:

  * complex CSR construction + SpMV (``csr_array @ x``),
  * complex CG on a Hermitian positive-definite system,
  * ``solve_ivp`` Schrodinger-style evolution (the quantum workload's
    composition, reference dispatch.h:53-75 c64 lane).

All complex host<->device movement goes through the stacked-real shims
(``utils.asjnp`` / ``utils.tohost``) — on the tunnel, complex arrays can
never cross the transfer boundary, so every input is shipped as two real
planes and recombined compiled, and every output is split compiled and
fetched real. Prints one JSON line: {"ok": true, ...}.
"""

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("JAX_PLATFORMS", "").lower() == "cpu":
    import jax

    jax.config.update("jax_platforms", "cpu")

import sparse_tpu as sparse
import sparse_tpu.linalg as linalg
from sparse_tpu import integrate
from sparse_tpu.utils import asjnp, tohost, transfer_restricted

n = 256
rng = np.random.default_rng(0)

# Hermitian tridiagonal H (a 1-D hopping Hamiltonian)
hop = (rng.random(n - 1) + 1j * rng.random(n - 1)).astype(np.complex64)
diag = np.full(n, 2.0, dtype=np.complex64)
H = sparse.diags([np.conj(hop), diag, hop], [-1, 0, 1]).tocsr()

x = (rng.random(n) + 1j * rng.random(n)).astype(np.complex64)
y = tohost(H @ asjnp(x))
# host oracle
import scipy.sparse as sp

Hs = sp.diags([np.conj(hop), diag, hop], [-1, 0, 1]).tocsr()
spmv_err = float(np.linalg.norm(y - Hs @ x) / np.linalg.norm(Hs @ x))

# Hermitian positive definite: H + 4I
A = sparse.diags([np.conj(hop), diag + 4.0, hop], [-1, 0, 1]).tocsr()
b = x
xs, iters = linalg.cg(A, b, tol=1e-5, maxiter=500)
As = sp.diags([np.conj(hop), diag + 4.0, hop], [-1, 0, 1]).tocsr()
cg_resid = float(np.linalg.norm(As @ tohost(xs) - b) / np.linalg.norm(b))

# Schrodinger evolution: i dpsi/dt = H psi
psi0 = np.zeros(n, dtype=np.complex64)
psi0[n // 2] = 1.0
out = integrate.solve_ivp(
    lambda t, psi: -1j * (H @ psi), (0.0, 0.5), psi0,
    method="RK45", rtol=1e-6, atol=1e-8,
)
psiT = tohost(out.y)[:, -1]
norm_drift = float(abs(np.linalg.norm(psiT) - 1.0))

rec = {
    "ok": bool(spmv_err < 1e-5 and cg_resid < 1e-4 and norm_drift < 1e-3),
    "platform": __import__("jax").devices()[0].platform,
    "transfer_restricted": transfer_restricted(),
    "spmv_rel_err": spmv_err,
    "cg_resid": cg_resid,
    "cg_iters": int(iters),
    "norm_drift": norm_drift,
}
print(json.dumps(rec))
sys.exit(0 if rec["ok"] else 1)
