#!/usr/bin/env python
"""Run the live Axon serving exporter (telemetry/_serve.py) as a CLI.

Usage:
    python scripts/axon_serve.py [--port 9109] [--host 127.0.0.1] [--once]

Starts ``telemetry.serve()`` — a daemon-threaded stdlib HTTP server —
and blocks until Ctrl-C. Endpoints (docs/telemetry.md, "operating a
serving session"):

    /metrics   Prometheus text exposition of the always-on registry
               (plan-cache counters, batch-service levels, per-ticket
               latency histograms, per-program compile/flops gauges)
    /healthz   JSON: health-monitor anomalies, kernel-failover latch
               states, fault-injection status, uptime
    /session   JSON: queue depth, bucket occupancy, per-session ticket
               states, compiled-program attribution, cold-start budget
    /alerts    JSON: the SLO watchdog's rule states (firing set, values,
               thresholds) — a disabled stub when no watchdog runs

``--once`` starts the server on the requested port (0 = ephemeral),
self-scrapes every endpoint, prints a one-line digest per endpoint
and exits 0 — the hand-run smoke check. The bound port is always
printed explicitly: when the requested port is taken, the exporter
falls back to an ephemeral one (ISSUE 11 satellite — the CI-rerun
flaky-port fix) and the printed port is the one that actually answers. In-process serving (the normal
deployment: the process running the SolveSession calls
``telemetry.serve()`` itself) needs no CLI; this script exists for
ad-hoc inspection of a long-lived python -i / notebook session exposing
the library via the same process, and as the documented entry point.

Exit codes: 0 = clean shutdown / --once ok, 2 = bad usage.
"""

from __future__ import annotations

import json
import os
import sys
import urllib.request

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)


def main(argv) -> int:
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    args = list(argv)
    once = "--once" in args
    if once:
        args.remove("--once")

    def take(flag, default):
        if flag in args:
            i = args.index(flag)
            if i + 1 >= len(args):
                print(f"axon_serve: {flag} needs a value", file=sys.stderr)
                raise SystemExit(2)
            v = args[i + 1]
            del args[i:i + 2]
            return v
        return default

    host = take("--host", "127.0.0.1")
    try:
        port = int(take("--port", "0" if once else "9109"))
    except ValueError:
        print("axon_serve: --port must be an integer", file=sys.stderr)
        return 2
    if args:
        print(f"axon_serve: unknown arguments {args}", file=sys.stderr)
        return 2

    sys.path.insert(0, REPO)
    from sparse_tpu import telemetry

    server = telemetry.serve(port=port, host=host)
    print(f"axon_serve: listening on {server.url} "
          "(/metrics /healthz /session /alerts)")
    # the actually-bound port, machine-greppable (it differs from the
    # request when the port was busy and the server fell back)
    print(
        f"axon_serve: bound port {server.port}"
        + (f" (requested {server.requested_port} busy)"
           if getattr(server, "fallback", False) else "")
    )
    if once:
        for ep in ("/metrics", "/healthz", "/session", "/alerts"):
            body = urllib.request.urlopen(server.url + ep, timeout=5).read()
            if ep == "/metrics":
                n = sum(
                    1 for ln in body.decode().splitlines()
                    if ln and not ln.startswith("#")
                )
                print(f"  {ep}: {n} series")
            else:
                payload = json.loads(body)
                keys = ", ".join(sorted(payload))
                print(f"  {ep}: {{{keys}}}")
        server.stop()
        return 0
    try:
        while True:
            import time

            time.sleep(3600)
    except KeyboardInterrupt:
        print("axon_serve: shutting down")
        server.stop()
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
