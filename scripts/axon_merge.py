#!/usr/bin/env python
"""Merge N per-process telemetry logs into one clock-aligned session log.

Under multi-controller the JSONL sink splits per process
(``records.<pid>.jsonl`` — sparse_tpu/telemetry/_recorder.py), and each
file leads with a ``session.start`` record carrying the process identity
(``pi``/``pid``) plus the session clock base: the wall-clock ``epoch``
and the ``mono``tonic reading taken at that same instant. Every event
additionally carries ``tm``, its monotonic offset since session start.
This script recombines the files into ONE session log that
``axon_trace`` renders with per-process lanes and ``axon_report``
analyzes/compares as usual.

Usage:
    python scripts/axon_merge.py [FILES_OR_GLOBS...]
        [-o OUT.jsonl]        # default results/axon/records.merged.jsonl
        [--align wall|session]
        [--json]              # print the summary as JSON
        [--quiet]

Clock alignment (per event): ``ts' = anchor + tm`` where ``tm`` is the
event's monotonic offset —

* ``wall`` (default): ``anchor`` is the file's own session epoch. Events
  keep real wall-clock placement but become monotonic-consistent within
  each process (NTP steps mid-session cannot reorder a process's lane).
* ``session``: every ``anchor`` is the EARLIEST session epoch across the
  inputs — all sessions start at a common origin. Use when the hosts'
  wall clocks are known-skewed and relative timing is what matters.

Events without ``tm`` (or files without a ``session.start``) keep their
raw ``ts``. Missing ``pi``/``pid`` stamps are backfilled from the file's
session.start (or the ``records.<pid>.jsonl`` filename), so the merged
trace never renders an unattributed lane. bench.py hardware records (no
``kind``) pass through on raw ``ts``. Exit codes: 0 ok, 2 bad usage /
no input files.
"""

from __future__ import annotations

import glob as _glob
import json
import os
import re
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_GLOB = os.path.join(REPO, "results", "axon", "records*.jsonl")
DEFAULT_OUT = os.path.join(REPO, "results", "axon", "records.merged.jsonl")

_PID_NAME = re.compile(r"\.(\d+)\.jsonl$")


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) else None


def load_process_log(path: str) -> dict:
    """One per-process file -> ``{"path", "anchor", "records"}`` where
    ``anchor`` is the first ``session.start`` (or None) and ``records``
    every parsed JSON line (unparseable lines are dropped — the merged
    log must stay machine-clean)."""
    records = []
    anchor = None
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            records.append(rec)
            if (
                anchor is None
                and rec.get("kind") == "session.start"
                and _num(rec.get("epoch")) is not None
            ):
                anchor = rec
    if anchor is None:
        m = _PID_NAME.search(os.path.basename(path))
        if m:  # identity from the sink-split filename, clockless
            anchor = {"pid": int(m.group(1))}
    return {"path": path, "anchor": anchor, "records": records}


def merge_logs(logs, align: str = "wall"):
    """Merge loaded per-process logs (see :func:`load_process_log`) into
    one ts-sorted record list; returns ``(records, summary)``."""
    ref_epoch = None
    for lg in logs:
        ep = _num((lg["anchor"] or {}).get("epoch"))
        if ep is not None and (ref_epoch is None or ep < ref_epoch):
            ref_epoch = ep

    merged = []
    summary = {"files": [], "events": 0, "passthrough": 0, "align": align}
    for lg in logs:
        anchor = lg["anchor"] or {}
        epoch = _num(anchor.get("epoch"))
        base = (
            ref_epoch if (align == "session" and ref_epoch is not None)
            else epoch
        )
        n_ev = 0
        for rec in lg["records"]:
            rec = dict(rec)
            if "kind" in rec:
                n_ev += 1
                tm = _num(rec.get("tm"))
                if tm is not None and base is not None:
                    rec["ts"] = base + tm
                if "pi" not in rec and "pi" in anchor:
                    rec["pi"] = anchor["pi"]
                if "pid" not in rec and "pid" in anchor:
                    rec["pid"] = anchor["pid"]
            else:
                summary["passthrough"] += 1
            merged.append(rec)
        summary["files"].append({
            "path": os.path.basename(lg["path"]),
            "events": n_ev,
            "pi": anchor.get("pi"),
            "pid": anchor.get("pid"),
            "epoch": epoch,
            "offset_s": round(epoch - ref_epoch, 6)
            if (epoch is not None and ref_epoch is not None) else None,
        })
        summary["events"] += n_ev
    merged.sort(key=lambda r: _num(r.get("ts")) or 0.0)
    summary["processes"] = len({
        f["pid"] for f in summary["files"] if f["pid"] is not None
    })
    return merged, summary


def merge_files(paths, out_path: str, align: str = "wall"):
    """Load, merge and write; returns the summary dict."""
    logs = [load_process_log(p) for p in paths]
    merged, summary = merge_logs(logs, align=align)
    d = os.path.dirname(os.path.abspath(out_path))
    os.makedirs(d, exist_ok=True)
    with open(out_path, "w") as f:
        for rec in merged:
            f.write(json.dumps(rec) + "\n")
    summary["out"] = out_path
    return summary


def main(argv) -> int:
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    args = list(argv)
    quiet = "--quiet" in args
    as_json = "--json" in args
    for flag in ("--quiet", "--json"):
        while flag in args:
            args.remove(flag)

    def take(flag, default=None):
        if flag in args:
            i = args.index(flag)
            if i + 1 >= len(args):
                print(f"axon_merge: {flag} needs a value", file=sys.stderr)
                raise SystemExit(2)
            v = args[i + 1]
            del args[i:i + 2]
            return v
        return default

    out = take("-o", take("--out", DEFAULT_OUT))
    align = take("--align", "wall")
    if align not in ("wall", "session"):
        print("axon_merge: --align must be 'wall' or 'session'",
              file=sys.stderr)
        return 2

    patterns = args if args else [DEFAULT_GLOB]
    paths = []
    for pat in patterns:
        hits = sorted(_glob.glob(pat))
        paths.extend(hits if hits else [pat])
    # never fold a previous merge output back into itself
    out_abs = os.path.abspath(out)
    paths = [p for p in dict.fromkeys(paths) if os.path.abspath(p) != out_abs]
    missing = [p for p in paths if not os.path.exists(p)]
    if missing or not paths:
        for p in missing:
            print(f"axon_merge: no such file {p}", file=sys.stderr)
        if not paths:
            print("axon_merge: no input files", file=sys.stderr)
        return 2

    summary = merge_files(paths, out, align=align)
    if as_json:
        print(json.dumps(summary, sort_keys=True))
    elif not quiet:
        print(
            f"axon_merge: {len(summary['files'])} file(s), "
            f"{summary['processes']} process(es), {summary['events']} events "
            f"(+{summary['passthrough']} bench records) -> {out}"
        )
        for f in summary["files"]:
            off = (
                f"+{f['offset_s']}s" if f["offset_s"] is not None else "no clock"
            )
            print(
                f"  {f['path']:<28} pi={f['pi']} pid={f['pid']} "
                f"events={f['events']} ({off})"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
