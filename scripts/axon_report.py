#!/usr/bin/env python
"""Offline session analyzer: join records.jsonl with BENCH_r*.json,
roll up latencies / comm volumes / cache behavior / anomalies, and
(optionally) flag regressions against a baseline report.

Usage:
    python scripts/axon_report.py [records.jsonl]
        [--bench BENCH_r05.json ...]   # join bench evidence files
        [--json OUT.json]              # write the machine report
        [--compare BASELINE.json]      # a report written by --json
        [--threshold 0.2]              # relative regression gate
        [--peak-gflops G] [--peak-gbs B]  # roofline ceilings (optional)
        [--peak-ici-gbs I]             # per-shard interconnect ceiling
        [--quiet]
    python scripts/axon_report.py --trend [BENCH_r*.json globs]
        # cross-round bench trend table (no session log needed)
    python scripts/axon_report.py --history [SEGMENTS_DIR]
        # join the v7 history segments across restarts: sessions, span,
        # and the SLO-miss incident window (results/axon/history)

Exit codes: 0 = ok, 1 = regressions found (--compare), 2 = bad usage /
missing input — so ``axon_report --compare`` gates CI directly.

Pure-stdlib on purpose: no sparse_tpu import, no jax init — the report
reads the same JSONL/JSON artifacts the repo already commits, in
milliseconds (the quick-lane smoke runs it against the committed
``results/axon/records.jsonl`` every test run).

The comparable surface is ``report["metrics"]``: a flat
``{name: {"v": value, "hib": higher_is_better}}`` dict covering span
latencies (p50/p95), per-solver iteration means, comm volumes, anomaly
counts, per-ticket latency percentiles (p50/p95/p99) + SLO misses, the
session's cold-start compile budget, and joined bench metric values.
``--compare`` flags any metric that moved against its direction by more
than ``--threshold`` (relative) and exits 1.

Axon v3 additions (ISSUE 6): ``report["tickets"]`` rolls up the
``batch.ticket`` terminal events (states, requeues, SLO misses, latency
percentiles, mean phase breakdown); ``report["programs"]`` joins each
``plan_cache.compile`` attribution (compile seconds, XLA flops / bytes
/ peak HBM) with the measured ``batch.dispatch`` solve wall time of the
same program into an achieved GFLOP/s / GB/s table (+ percent-of-peak
when ``--peak-gflops`` / ``--peak-gbs`` ceilings are given);
``report["cold_start_s"]`` is the total compile+pack seconds the
session paid — the number ROADMAP item 4 (persistent plan cache) is
out to kill.

Axon v5 additions (ISSUE 11): ``report["load"]`` rolls up the
``loadgen.trace`` events (offered vs achieved req/s, latency
percentiles, SLO-miss rate, the weighted tenant-fairness index) and
``report["alerts"]`` the ``watchdog.alert``/``watchdog.clear`` chains
(fired/cleared per rule, unresolved alerts); the bench ``sustained_cg``
row (achieved req/s at the p95 SLO under a seeded Poisson trace) is
lifted onto the ``--compare`` surface next to ``batched_cg`` /
``fleet_batched_cg``. ``--compare`` additionally reports metrics
present on only ONE side (a baseline from before a new bench row, or a
row that vanished) as *informational* — listed, never gated: only a
metric present in BOTH reports can regress.

Axon v6 additions (ISSUE 12): the ``programs`` table gains MEASURED
device-time columns from the sampled timed-dispatch path
(``SPARSE_TPU_PROFILE_EVERY`` — ``batch.dispatch`` events carrying
``device_ms``/``host_ms``): per-program ``device_ms_mean`` /
``host_ms_mean`` / ``device_samples`` and the device-clock achieved
rate ``achieved_gflops_dev`` next to the host-wall analytic roofline;
``program.<key>.device_ms_mean`` rides ``--compare``. ``--trend`` joins
every committed ``BENCH_r*.json`` into a cross-round table
(``cg_iters_per_s``, ``sustained_cg.achieved_rps``, ``cold_start``
times, batched/fleet speedups) so the bench trajectory in ROADMAP is
machine-generated. ``scripts/axon_doctor.py`` is the sibling analyzer
for incident bundles (``results/axon/incidents/``).

Axon v7 additions (ISSUE 19): ``report["usage"]`` rolls up per-tenant
usage metering from the ``batch.ticket``/``ingest.ticket`` terminal
events plus sampled-dispatch device time; ``report["budget"]``
recomputes the SLO error-budget burn rates offline (same objective and
multi-window math as ``telemetry/_budget.py``, reimplemented inline —
this script never imports sparse_tpu) and lifts
``budget.fast_burn_max`` / ``budget.slow_burn_max`` /
``usage.device_ms_total`` onto the ``--compare`` surface. ``--history``
joins the on-disk history segments across process restarts and prints
the SLO-miss incident window.

Elastic-mesh additions (ISSUE 20): the bench ``remesh`` row (the
topology-change tax — time-to-first-solve after a shrink, cold vs
mesh-keyed-manifest-warm re-plan, zero-miss warm gate) rides both the
``--compare`` surface (``remesh.*``) and the ``--trend`` table.

Axon v4 additions (ISSUE 7): ``report["comm"]`` rolls up the
``comm.measured`` events (parallel/comm.py trace-time accounting) per
site — measured vs analytic-model bytes, divergence %, and the achieved
per-shard interconnect GB/s joined with the ``--peak-ici-gbs`` ceiling.
``comm.<site>.abs_divergence_pct`` rides the ``--compare`` metrics
surface, so a model/implementation drift (or an unaccounted collective)
fails the regression gate like any latency regression would.
"""

from __future__ import annotations

import bisect
import glob as _glob
import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_RECORDS = os.path.join(REPO, "results", "axon", "records.jsonl")
DEFAULT_HISTORY = os.path.join(REPO, "results", "axon", "history")

#: the SLO objective and burn windows (seconds) — must mirror
#: telemetry/_budget.py (this script recomputes the math inline)
_OBJECTIVE = 0.99
_FAST_WINDOWS = (300.0, 3600.0)
_SLOW_WINDOWS = (21600.0, 259200.0)


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def load_records(path: str) -> tuple:
    """(telemetry events, bench hardware-metric records) of a session
    log; unparseable lines are skipped (evidence files survive partial
    writes)."""
    events, hw = [], []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rec = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(rec, dict):
                continue
            if "kind" in rec:
                events.append(rec)
            elif isinstance(rec.get("metric"), str):
                hw.append(rec)
    return events, hw


def load_bench_files(paths) -> list:
    """``{"metric", "value", "unit", "source"}`` rows from BENCH_r*.json
    style evidence files (the committed round artifacts: a ``parsed``
    metric dict per file)."""
    rows = []
    for path in paths:
        try:
            data = json.load(open(path))
        except (OSError, json.JSONDecodeError):
            continue
        parsed = data.get("parsed") if isinstance(data, dict) else None
        if isinstance(parsed, dict) and isinstance(parsed.get("metric"), str):
            rows.append({
                "metric": parsed["metric"],
                "value": parsed.get("value"),
                "unit": parsed.get("unit"),
                "source": os.path.basename(path),
            })
    return rows


# ---------------------------------------------------------------------------
# rollups
# ---------------------------------------------------------------------------
def _percentile(sorted_vals: list, q: float) -> float:
    if not sorted_vals:
        return 0.0
    idx = min(int(q * (len(sorted_vals) - 1) + 0.5), len(sorted_vals) - 1)
    return sorted_vals[idx]


def _num(v):
    return v if isinstance(v, (int, float)) and not isinstance(v, bool) else None


_TICKET_PHASES = ("queue", "pack", "compile", "solve", "readback")


def _tickets_rollup(events) -> dict:
    """Per-ticket latency/SLO rollup from ``batch.ticket`` terminal
    events: states, requeues, SLO misses, p50/p95/p99 latency and the
    mean phase breakdown (the serving-path waterfall)."""
    evs = [e for e in events if e.get("kind") == "batch.ticket"]
    lats = sorted(
        float(e["latency_ms"]) for e in evs
        if _num(e.get("latency_ms")) is not None
    )
    states: dict = {}
    by_solver: dict = {}
    phase_tot: dict = {}
    phase_n: dict = {}
    requeued = slo_misses = 0
    for e in evs:
        states[str(e.get("state", "?"))] = (
            states.get(str(e.get("state", "?")), 0) + 1
        )
        by_solver[str(e.get("solver", "?"))] = (
            by_solver.get(str(e.get("solver", "?")), 0) + 1
        )
        if e.get("requeued"):
            requeued += 1
        if e.get("slo_miss"):
            slo_misses += 1
        ph = e.get("phases")
        if isinstance(ph, dict):
            for p in _TICKET_PHASES:
                v = _num(ph.get(f"{p}_ms"))
                if v is not None:
                    phase_tot[p] = phase_tot.get(p, 0.0) + v
                    phase_n[p] = phase_n.get(p, 0) + 1
    return {
        "n": len(evs),
        "states": states,
        "by_solver": by_solver,
        "requeued": requeued,
        "slo_misses": slo_misses,
        "latency_ms": {
            "p50": round(_percentile(lats, 0.50), 3),
            "p95": round(_percentile(lats, 0.95), 3),
            "p99": round(_percentile(lats, 0.99), 3),
            "max": round(lats[-1], 3) if lats else 0.0,
            "mean": round(sum(lats) / len(lats), 3) if lats else 0.0,
        },
        "phase_ms_mean": {
            p: round(phase_tot[p] / phase_n[p], 3) for p in phase_tot
        },
    }


#: the headline fields a loadgen.trace event carries (ISSUE 11)
_LOAD_FIELDS = ("trace", "arrivals", "completed", "failed", "wall_s",
                "offered_rps", "achieved_rps", "p50_ms", "p95_ms",
                "p99_ms", "slo_ms", "slo_miss_rate", "fairness",
                "dispatches")


def _load_rollup(events) -> dict:
    """Loadgen runs (``loadgen.trace`` events): run count plus the most
    recent run's headline numbers and per-tenant shares — the
    throughput/latency/fairness picture of the last load test."""
    evs = [e for e in events if e.get("kind") == "loadgen.trace"]
    if not evs:
        return {"runs": 0}
    last = max(evs, key=lambda e: e.get("ts", 0))
    out = {
        "runs": len(evs),
        "last": {k: last[k] for k in _LOAD_FIELDS if k in last},
    }
    if isinstance(last.get("tenants"), dict):
        out["last"]["tenants"] = last["tenants"]
    return out


def _alerts_rollup(events) -> dict:
    """Watchdog alert chains: fired/cleared counts per rule (from the
    ``watchdog.alert``/``watchdog.clear`` events), rules whose last
    transition was an unresolved alert, and the worst severity seen."""
    by_rule: dict = {}
    for e in events:
        kind = e.get("kind")
        if kind not in ("watchdog.alert", "watchdog.clear"):
            continue
        r = by_rule.setdefault(str(e.get("rule", "?")), {
            "fired": 0, "cleared": 0, "severity": None, "last": None,
        })
        if kind == "watchdog.alert":
            r["fired"] += 1
            r["severity"] = e.get("severity")
            r["last"] = "alert"
        else:
            r["cleared"] += 1
            r["last"] = "clear"
    fired = sum(r["fired"] for r in by_rule.values())
    cleared = sum(r["cleared"] for r in by_rule.values())
    return {
        "fired": fired,
        "cleared": cleared,
        "by_rule": by_rule,
        "unresolved": sorted(
            name for name, r in by_rule.items() if r["last"] == "alert"
        ),
    }


def _autopilot_rollup(events) -> dict:
    """The policy-tuner table (ISSUE 16): per tuning group, the chosen
    arm (last ``autopilot.converge``/``autopilot.restore`` wins), how
    it was pinned (tuned online vs restored from the vault), trial
    counts, per-arm measured score medians from ``autopilot.trial``
    events, and the measured REGRET of the chosen arm vs the
    best-scoring candidate (1.0 == picked the fastest measured arm;
    ``None`` while a group is still exploring). ``reopens``/``aborts``
    count the loop's churn — drift re-explorations and SLO-guard
    kills."""
    groups: dict = {}
    for e in events:
        kind = str(e.get("kind", ""))
        if not kind.startswith("autopilot."):
            continue
        g = groups.setdefault(str(e.get("group", "?")), {
            "chosen": None, "chosen_score_ms": None, "source": None,
            "trials": 0, "arms": {}, "converges": 0, "restores": 0,
            "reopens": 0, "aborts": 0,
        })
        if kind == "autopilot.trial":
            g["trials"] += 1
            if _num(e.get("score_ms")) is not None:
                g["arms"].setdefault(str(e.get("arm", "?")), []).append(
                    float(e["score_ms"])
                )
        elif kind == "autopilot.converge":
            g["converges"] += 1
            g["chosen"] = e.get("arm")
            g["chosen_score_ms"] = _num(e.get("score_ms"))
            g["source"] = "tuned"
        elif kind == "autopilot.restore":
            g["restores"] += 1
            g["chosen"] = e.get("arm")
            g["chosen_score_ms"] = _num(e.get("score_ms"))
            g["source"] = "restored"
        elif kind == "autopilot.reopen":
            g["reopens"] += 1
        elif kind == "autopilot.abort":
            g["aborts"] += 1
    for g in groups.values():
        meds = {
            arm: round(_percentile(sorted(scores), 0.50), 4)
            for arm, scores in sorted(g["arms"].items())
        }
        g["arms"] = meds
        best = min(meds.values()) if meds else None
        sc = g["chosen_score_ms"]
        g["regret"] = (
            round(sc / best, 3)
            if best is not None and sc is not None and best > 0 else None
        )
    return {
        "n_groups": len(groups),
        "converged": sum(
            1 for g in groups.values() if g["chosen"] is not None
        ),
        "trials": sum(g["trials"] for g in groups.values()),
        "reopens": sum(g["reopens"] for g in groups.values()),
        "aborts": sum(g["aborts"] for g in groups.values()),
        "groups": groups,
    }


def _programs_rollup(events, peak_gflops=None, peak_gbs=None) -> dict:
    """The achieved-vs-roofline table: ``plan_cache.compile``
    attribution (compile wall-clock, XLA flops/bytes/peak HBM per
    program) joined with measured ``batch.dispatch`` solve wall time of
    the same program key. Achieved rates use total flops moved over
    total solve seconds; ``--peak-*`` ceilings add percent-of-roofline
    columns.

    Axon v6 (ISSUE 12): sampled timed dispatches carry a measured
    host-vs-device split (``device_ms``/``host_ms`` fields, the
    ``SPARSE_TPU_PROFILE_EVERY`` path) — those aggregate into
    ``device_ms_mean``/``host_ms_mean``/``device_samples`` and a
    device-clock achieved rate (``achieved_gflops_dev``), the *measured*
    column next to the analytic roofline."""
    programs: dict = {}
    for e in events:
        if e.get("kind") != "plan_cache.compile":
            continue
        key = str(e.get("program", "?"))
        p = programs.setdefault(key, {"solves": 0, "solve_ms_total": 0.0})
        for f in ("solver", "bucket", "dtype", "n", "nnz", "flops",
                  "bytes", "peak_bytes", "compile_s", "pack_s"):
            if f in e:
                p[f] = e[f]
    for e in events:
        if e.get("kind") != "batch.dispatch" or "program" not in e:
            continue
        key = str(e["program"])
        p = programs.setdefault(key, {"solves": 0, "solve_ms_total": 0.0})
        p["solves"] += 1
        sm = _num(e.get("solve_ms"))
        if sm is not None:
            p["solve_ms_total"] = round(p["solve_ms_total"] + sm, 3)
        dm = _num(e.get("device_ms"))
        if dm is not None:  # a sampled timed dispatch
            p["device_ms_total"] = round(
                p.get("device_ms_total", 0.0) + dm, 3
            )
            p["device_samples"] = p.get("device_samples", 0) + 1
            hm = _num(e.get("host_ms"))
            if hm is not None:
                p["host_ms_total"] = round(
                    p.get("host_ms_total", 0.0) + hm, 3
                )
    for p in programs.values():
        solve_s = p["solve_ms_total"] / 1e3
        flops, nbytes = _num(p.get("flops")), _num(p.get("bytes"))
        if solve_s > 0 and p["solves"]:
            if flops:
                p["achieved_gflops"] = round(
                    flops * p["solves"] / solve_s / 1e9, 4
                )
                if peak_gflops:
                    p["pct_peak_gflops"] = round(
                        100.0 * p["achieved_gflops"] / peak_gflops, 2
                    )
            if nbytes:
                p["achieved_gbs"] = round(
                    nbytes * p["solves"] / solve_s / 1e9, 4
                )
                if peak_gbs:
                    p["pct_peak_gbs"] = round(
                        100.0 * p["achieved_gbs"] / peak_gbs, 2
                    )
        samples = p.get("device_samples", 0)
        if samples:
            p["device_ms_mean"] = round(p["device_ms_total"] / samples, 3)
            if "host_ms_total" in p:
                p["host_ms_mean"] = round(
                    p["host_ms_total"] / samples, 3
                )
            if flops and p["device_ms_total"] > 0:
                # the measured-device-clock rate: flops over the time the
                # device actually ran (per sampled dispatch), not over
                # host wall that includes dispatch/trace overhead
                p["achieved_gflops_dev"] = round(
                    flops * samples / (p["device_ms_total"] / 1e3) / 1e9, 4
                )
        if flops and nbytes:
            # arithmetic intensity: which roofline regime the program
            # sits in (SpMV-shaped programs live far left of the ridge)
            p["flops_per_byte"] = round(flops / nbytes, 4)
    return programs


def _comm_rollup(events, peak_ici_gbs=None) -> dict:
    """Measured-vs-model collective accounting per site, from the
    ``comm.measured`` events: total measured bytes, the analytic model's
    bytes for the same solves, divergence %, and — when the events carry
    solve wall time — achieved per-shard GB/s vs the ``--peak-ici-gbs``
    interconnect roofline. ``exact=False`` marks sites whose accounting
    includes a capacity bound (ragged exchanges)."""
    sites: dict = {}
    for e in events:
        if e.get("kind") != "comm.measured":
            continue
        s = sites.setdefault(str(e.get("site", "?")), {
            "events": 0, "measured_bytes": 0, "model_bytes": 0,
            "bytes_per_shard": 0, "solve_s": 0.0, "executions": 0,
            "exact": True,
        })
        s["events"] += 1
        s["measured_bytes"] += int(_num(e.get("bytes")) or 0)
        s["model_bytes"] += int(_num(e.get("model_bytes")) or 0)
        s["bytes_per_shard"] += int(_num(e.get("bytes_per_shard")) or 0)
        s["solve_s"] += float(_num(e.get("solve_s")) or 0.0)
        s["executions"] += int(_num(e.get("executions")) or 0)
        if e.get("exact") is False:
            s["exact"] = False
    for s in sites.values():
        if s["model_bytes"]:
            s["divergence_pct"] = round(
                100.0 * (s["measured_bytes"] - s["model_bytes"])
                / s["model_bytes"], 3,
            )
        if s["solve_s"] > 0 and s["bytes_per_shard"]:
            s["achieved_gbs_per_shard"] = round(
                s["bytes_per_shard"] / s["solve_s"] / 1e9, 6
            )
            if peak_ici_gbs:
                s["pct_peak_ici"] = round(
                    100.0 * s["achieved_gbs_per_shard"] / peak_ici_gbs, 3
                )
        s["solve_s"] = round(s["solve_s"], 6)
    return sites


def _usage_rollup(events) -> dict:
    """Per-tenant usage metering from the session log (Axon v7): the
    offline mirror of ``telemetry._budget.usage_stats()`` — solve and
    ingest ticket counts + SLO misses per tenant (from the terminal
    events; ``'-'`` is the untagged pseudo-tenant) and the session's
    total sampled device time (``batch.dispatch`` timed dispatches,
    which carry no tenant at event level)."""
    tenants: dict = {}
    device_ms_total = 0.0

    def row(tenant):
        return tenants.setdefault(str(tenant) if tenant else "-", {})

    def bump(r, field, n=1):
        r[field] = r.get(field, 0) + n

    for e in events:
        k = e.get("kind")
        if k == "batch.ticket":
            r = row(e.get("tenant"))
            bump(r, "tickets")
            if e.get("slo_miss"):
                bump(r, "slo_misses")
        elif k == "ingest.ticket":
            bump(row(e.get("tenant")), "ingest")
        elif k == "batch.dispatch":
            dm = _num(e.get("device_ms"))
            if dm is not None:
                device_ms_total += dm
    out: dict = {"tenants": tenants} if tenants else {}
    if device_ms_total:
        out["device_ms_total"] = round(device_ms_total, 3)
    return out


def _burn_max(stream, windows, objective, min_total: int = 10):
    """Worst multi-window burn rate over one (ts, miss) stream: at every
    event instant, burn(W) = miss_rate over the trailing W seconds
    scaled by 1/(1-objective); the pair guards against stale spikes by
    taking the MIN across both windows (both must burn — the same
    semantics as ``_budget.Engine.worst_burn``), and the rollup keeps
    the max over time. Windows holding fewer than ``min_total`` tickets
    are not scored (one early missed ticket is not a 100x burn — the
    low-traffic discount every burn-rate alert applies). None when no
    window ever reaches ``min_total``."""
    if not stream:
        return None
    stream = sorted(stream)
    ts = [t for t, _ in stream]
    prefix = [0]
    for _, miss in stream:
        prefix.append(prefix[-1] + miss)
    denom = 1.0 - objective
    worst = None
    for i, t in enumerate(ts):
        pair = []
        for w in windows:
            j = bisect.bisect_left(ts, t - w, 0, i + 1)
            total = (i + 1) - j
            if total < min_total:
                break
            pair.append((prefix[i + 1] - prefix[j]) / total / denom)
        if len(pair) == len(windows):
            burn = min(pair)
            if worst is None or burn > worst:
                worst = burn
    return round(worst, 4) if worst is not None else None


def _budget_rollup(events, objective: float = _OBJECTIVE) -> dict:
    """The offline error-budget picture (Axon v7): per-tenant (plus the
    ``''`` aggregate) worst fast- and slow-window burn rates recomputed
    from the ``batch.ticket`` terminal events. Empty dict when the log
    has no SLO-tracked tickets (no ``slo_miss`` fields — a session run
    without ``slo_ms`` has no budget to burn)."""
    streams: dict = {}
    tracked = False
    for e in events:
        if e.get("kind") != "batch.ticket":
            continue
        ts = _num(e.get("ts"))
        if ts is None or "slo_miss" not in e:
            continue
        tracked = True
        miss = 1 if e.get("slo_miss") else 0
        keys = [""]
        if e.get("tenant"):
            keys.append(str(e["tenant"]))
        for key in keys:
            streams.setdefault(key, []).append((ts, miss))
    if not tracked:
        return {}
    tenants = {}
    for key, stream in sorted(streams.items()):
        tenants[key] = {
            "tickets": len(stream),
            "misses": sum(m for _, m in stream),
            "fast_burn_max": _burn_max(stream, _FAST_WINDOWS, objective),
            "slow_burn_max": _burn_max(stream, _SLOW_WINDOWS, objective),
        }
    agg = tenants.get("", {})
    return {
        "objective": objective,
        "fast_burn_max": agg.get("fast_burn_max"),
        "slow_burn_max": agg.get("slow_burn_max"),
        "tenants": tenants,
    }


def build_report(records_path: str, bench_paths=(), peak_gflops=None,
                 peak_gbs=None, peak_ici_gbs=None) -> dict:
    """The whole analysis as one JSON-serializable dict (see module
    docstring for the ``metrics`` comparison surface)."""
    events, hw = load_records(records_path)

    by_kind: dict = {}
    for e in events:
        by_kind[e["kind"]] = by_kind.get(e["kind"], 0) + 1

    # span latency table (from span events; the in-memory aggregates are
    # not in the log — the events are)
    span_durs: dict = {}
    for e in events:
        if e.get("kind") == "span" and _num(e.get("dur_s")) is not None:
            span_durs.setdefault(str(e.get("name", "?")), []).append(
                float(e["dur_s"])
            )
    spans = {}
    for name, durs in sorted(span_durs.items()):
        ds = sorted(durs)
        spans[name] = {
            "n": len(ds),
            "total_s": round(sum(ds), 6),
            "p50_s": round(_percentile(ds, 0.50), 9),
            "p95_s": round(_percentile(ds, 0.95), 9),
            "max_s": round(ds[-1], 9),
        }

    # per-solver rollup from solver.solve events
    solvers: dict = {}
    for e in events:
        if e.get("kind") != "solver.solve":
            continue
        s = solvers.setdefault(str(e.get("solver", "?")), {
            "solves": 0, "iters_total": 0, "paths": {},
        })
        s["solves"] += 1
        it = _num(e.get("iters"))
        s["iters_total"] += int(it) if it is not None else 0
        p = str(e.get("path", "?"))
        s["paths"][p] = s["paths"].get(p, 0) + 1
    for s in solvers.values():
        s["iters_mean"] = round(
            s["iters_total"] / s["solves"], 3
        ) if s["solves"] else 0.0

    # structural comm volumes
    comm_bytes: dict = {}
    for e in events:
        b = _num(e.get("bytes"))
        if b is not None and str(e.get("kind", "")).startswith("comm."):
            comm_bytes[e["kind"]] = comm_bytes.get(e["kind"], 0) + int(b)

    # plan-cache behavior: the last session embed is the session total;
    # batch.dispatch deltas attribute movement to the solve service
    sessions = [e for e in events if e.get("kind") == "bench.session"]
    cache = {"session": None, "batch_dispatch_delta": None}
    if sessions:
        last = max(sessions, key=lambda e: e.get("ts", 0))
        pc = last.get("plan_cache")
        if isinstance(pc, dict):
            cache["session"] = pc
    deltas = [
        e.get("plan_cache") for e in events
        if e.get("kind") == "batch.dispatch"
        and isinstance(e.get("plan_cache"), dict)
    ]
    if deltas:
        agg: dict = {}
        for d in deltas:
            for k, v in d.items():
                if _num(v) is not None:
                    agg[k] = agg.get(k, 0) + v
        cache["batch_dispatch_delta"] = agg

    anomalies = [
        {k: e.get(k) for k in ("ts", "solver", "reason", "iter", "lane",
                               "resid2", "path") if k in e}
        for e in events if e.get("kind") == "solver.anomaly"
    ]

    tickets = _tickets_rollup(events)
    usage = _usage_rollup(events)
    budget = _budget_rollup(events)
    comm = _comm_rollup(events, peak_ici_gbs)
    load = _load_rollup(events)
    alerts = _alerts_rollup(events)
    auto = _autopilot_rollup(events)
    programs = _programs_rollup(events, peak_gflops, peak_gbs)
    cold_start_s = round(sum(
        (_num(p.get("compile_s")) or 0.0) + (_num(p.get("pack_s")) or 0.0)
        for p in programs.values()
    ), 6)

    bench_rows = load_bench_files(bench_paths)
    for e in sessions:
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("metric"), str):
            bench_rows.append({
                "metric": rec["metric"], "value": rec.get("value"),
                "unit": rec.get("unit"), "source": "bench.session",
            })
    for rec in hw:
        bench_rows.append({
            "metric": rec["metric"], "value": rec.get("value"),
            "unit": rec.get("unit"), "source": "records.jsonl",
        })

    # -- the flat comparison surface ----------------------------------------
    metrics: dict = {}
    for name, st in spans.items():
        metrics[f"span.{name}.p50_s"] = {"v": st["p50_s"], "hib": False}
        metrics[f"span.{name}.p95_s"] = {"v": st["p95_s"], "hib": False}
    for name, s in solvers.items():
        metrics[f"solver.{name}.iters_mean"] = {
            "v": s["iters_mean"], "hib": False,
        }
    for kind, b in comm_bytes.items():
        metrics[f"bytes.{kind}"] = {"v": b, "hib": False}
    for site, s in comm.items():
        if _num(s.get("divergence_pct")) is not None and s.get("exact"):
            # measured drift from the analytic model: direction-free, so
            # compare |divergence| (a site can drift either way)
            metrics[f"comm.{site}.abs_divergence_pct"] = {
                "v": round(abs(s["divergence_pct"]), 3), "hib": False,
            }
        if _num(s.get("achieved_gbs_per_shard")) is not None:
            metrics[f"comm.{site}.achieved_gbs_per_shard"] = {
                "v": s["achieved_gbs_per_shard"], "hib": True,
            }
    metrics["anomalies.count"] = {"v": len(anomalies), "hib": False}
    if tickets["n"]:
        for q in ("p50", "p95", "p99"):
            metrics[f"tickets.latency_ms.{q}"] = {
                "v": tickets["latency_ms"][q], "hib": False,
            }
        metrics["tickets.slo_misses"] = {
            "v": tickets["slo_misses"], "hib": False,
        }
    if cold_start_s:
        metrics["cold_start_s"] = {"v": cold_start_s, "hib": False}
    # the loadgen surface (ISSUE 11): last run's throughput/latency/
    # fairness numbers ride --compare like every other latency metric
    if load.get("runs"):
        ll = load["last"]
        for key, hib in (("achieved_rps", True), ("p95_ms", False),
                         ("slo_miss_rate", False), ("fairness", True)):
            if _num(ll.get(key)) is not None:
                metrics[f"load.{key}"] = {"v": ll[key], "hib": hib}
    if alerts["fired"] or alerts["cleared"]:
        metrics["alerts.fired"] = {"v": alerts["fired"], "hib": False}
    # the v7 budget/usage surface: worst burn rates recomputed offline
    # and the session's sampled device time gate like latency metrics
    for k in ("fast_burn_max", "slow_burn_max"):
        if _num(budget.get(k)) is not None:
            metrics[f"budget.{k}"] = {"v": budget[k], "hib": False}
    if _num(usage.get("device_ms_total")) is not None:
        metrics["usage.device_ms_total"] = {
            "v": usage["device_ms_total"], "hib": False,
        }
    # the bench cold_start row (ISSUE 9): cold vs disk-warm vs warm
    # serving times ride the --compare surface so the vault's warm-
    # restart win is a pinned regression metric, not just a bench line
    cold_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("cold_start"), dict):
            cold_row = rec["cold_start"]
    if cold_row:
        for k in ("cold_s", "replay_s", "disk_warm_s", "warm_s"):
            if _num(cold_row.get(k)) is not None:
                metrics[f"cold_start.{k}"] = {"v": cold_row[k], "hib": False}
    # the bench sustained_cg row (ISSUE 11): achieved req/s at the p95
    # SLO under a seeded Poisson trace — the sustained-throughput
    # regression metric next to batched_cg/fleet_batched_cg
    sustained_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(
            rec.get("sustained_cg"), dict
        ):
            sustained_row = rec["sustained_cg"]
    if sustained_row:
        for k, hib in (("achieved_rps", True), ("offered_rps", True),
                       ("p95_ms", False), ("slo_miss_rate", False),
                       ("device_ms_mean", False),
                       # the streaming-dispatch comparison (ISSUE 13):
                       # same overloaded seeded trace, pipeline on/off
                       ("pipelined_rps", True), ("sync_rps", True),
                       ("pipeline_speedup", True),
                       # the v7 history sampler's measured tax on the
                       # same trace (acceptance bound: < 2%)
                       ("history_overhead_pct", False)):
            if _num(sustained_row.get(k)) is not None:
                metrics[f"sustained_cg.{k}"] = {
                    "v": sustained_row[k], "hib": hib,
                }
    # the bench fleet_batched_cg row (ISSUE 10): mesh-sharded vs single-
    # device serving on the batched_cg workload — warm wall times, the
    # sharded speedup, and the |measured-vs-model| psum divergence all
    # ride the --compare surface
    fleet_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(
            rec.get("fleet_batched_cg"), dict
        ):
            fleet_row = rec["fleet_batched_cg"]
    if fleet_row:
        for k in ("single_warm_s", "fleet_warm_s"):
            if _num(fleet_row.get(k)) is not None:
                metrics[f"fleet_batched_cg.{k}"] = {
                    "v": fleet_row[k], "hib": False,
                }
        if _num(fleet_row.get("speedup_warm")) is not None:
            metrics["fleet_batched_cg.speedup_warm"] = {
                "v": fleet_row["speedup_warm"], "hib": True,
            }
        if _num(fleet_row.get("divergence_pct")) is not None:
            metrics["fleet_batched_cg.abs_divergence_pct"] = {
                "v": abs(fleet_row["divergence_pct"]), "hib": False,
            }
    # the bench precond_cg row (ISSUE 14): end-to-end preconditioned
    # batched solve time on the ill-conditioned PDE profile — the
    # iteration-COUNT regression surface (everything else above tracks
    # per-iteration throughput)
    precond_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(
            rec.get("precond_cg"), dict
        ):
            precond_row = rec["precond_cg"]
    if precond_row:
        for k, hib in (("end_to_end_s", False), ("iters_mean", False),
                       ("build_s", False), ("speedup", True)):
            if _num(precond_row.get(k)) is not None:
                metrics[f"precond_cg.{k}"] = {
                    "v": precond_row[k], "hib": hib,
                }
    # the bench mixed_cg row (ISSUE 15): end-to-end mixed-precision
    # batched solve time on the pde512 banded profile — the PRECISION
    # regression surface (f32+IR / bf16-storage vs exact f64 at
    # matching achieved residual, plus the values-bytes column)
    mixed_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("mixed_cg"), dict):
            mixed_row = rec["mixed_cg"]
    if mixed_row:
        for k, hib in (("exact_s", False), ("f32ir_s", False),
                       ("bf16ir_s", False), ("speedup", True),
                       ("speedup_bf16", True),
                       ("bytes_ratio_bf16", True)):
            if _num(mixed_row.get(k)) is not None:
                metrics[f"mixed_cg.{k}"] = {"v": mixed_row[k], "hib": hib}
    # the bench auto_cg row (ISSUE 16): the online policy tuner's pick
    # quality (worst regret vs the best measured static across
    # profiles) and its headline win over the single global default —
    # informational vs older baselines, gated once both sides carry it
    auto_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("auto_cg"), dict):
            auto_row = rec["auto_cg"]
    if auto_row:
        for k, hib in (("regret_worst", False),
                       ("ill_speedup_vs_global", True)):
            if _num(auto_row.get(k)) is not None:
                metrics[f"auto_cg.{k}"] = {"v": auto_row[k], "hib": hib}
    # the bench ingest row (ISSUE 18): the streaming ingestion data
    # plane — rows/s through the sharded samplesort, cold-onboarding
    # wall vs the dedup-hit re-arrival (whose plan_misses must stay 0)
    ingest_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("ingest"), dict):
            ingest_row = rec["ingest"]
    if ingest_row:
        for k, hib in (("sort_rows_per_s", True),
                       ("cold_onboard_ms", False),
                       ("dedup_onboard_ms", False),
                       ("dedup_speedup", True),
                       ("dedup_plan_misses", False)):
            if _num(ingest_row.get(k)) is not None:
                metrics[f"ingest.{k}"] = {"v": ingest_row[k], "hib": hib}
    # the bench remesh row (ISSUE 20): the elastic-topology tax —
    # time-to-first-solve after a shrink, cold vs mesh-keyed-manifest-
    # warm re-plan (whose serving misses must stay 0), and the warm
    # replay count — pinned next to cold_start's restart surface
    remesh_row = None
    for e in sorted(sessions, key=lambda e: e.get("ts", 0)):
        rec = e.get("record")
        if isinstance(rec, dict) and isinstance(rec.get("remesh"), dict):
            remesh_row = rec["remesh"]
    if remesh_row:
        for k, hib in (("shrink_cold_s", False),
                       ("shrink_warm_s", False),
                       ("shrink_warm_replan_ms", False),
                       ("shrink_warm_misses", False),
                       ("replayed", True)):
            if _num(remesh_row.get(k)) is not None:
                metrics[f"remesh.{k}"] = {"v": remesh_row[k], "hib": hib}
    for key, p in programs.items():
        if _num(p.get("achieved_gflops")) is not None:
            metrics[f"program.{key}.achieved_gflops"] = {
                "v": p["achieved_gflops"], "hib": True,
            }
        # the measured device clock (sampled dispatches): a per-program
        # device-time regression gates like any latency metric
        if _num(p.get("device_ms_mean")) is not None:
            metrics[f"program.{key}.device_ms_mean"] = {
                "v": p["device_ms_mean"], "hib": False,
            }
    if cache["session"] and _num(cache["session"].get("hit_rate")) is not None:
        metrics["plan_cache.hit_rate"] = {
            "v": cache["session"]["hit_rate"], "hib": True,
        }
    seen_bench = set()
    for row in bench_rows:
        v = _num(row.get("value"))
        # first occurrence wins: explicit --bench files outrank embeds
        if v is not None and row["metric"] not in seen_bench:
            seen_bench.add(row["metric"])
            metrics[f"bench.{row['metric']}"] = {"v": v, "hib": True}

    return {
        "records": os.path.relpath(records_path, REPO)
        if records_path.startswith(REPO) else records_path,
        "events_total": len(events),
        "events_by_kind": dict(sorted(by_kind.items())),
        "spans": spans,
        "solvers": solvers,
        "comm_bytes": comm_bytes,
        "comm": comm,
        "cache": cache,
        "anomalies": anomalies[:100],
        "tickets": tickets,
        "usage": usage,
        "budget": budget,
        "load": load,
        "alerts": alerts,
        "programs": programs,
        "cold_start_s": cold_start_s,
        "cold_start_row": cold_row,
        "fleet_row": fleet_row,
        "sustained_row": sustained_row,
        "precond_row": precond_row,
        "mixed_row": mixed_row,
        "auto_row": auto_row,
        "ingest_row": ingest_row,
        "autopilot": auto,
        "bench": bench_rows,
        "metrics": metrics,
    }


# ---------------------------------------------------------------------------
# bench trend (ISSUE 12 satellite): join BENCH_r*.json across rounds
# ---------------------------------------------------------------------------
#: embedded bench rows lifted into the trend table, with headline keys
_TREND_EMBEDS = (
    ("sustained_cg", ("achieved_rps", "offered_rps", "p95_ms",
                      "slo_miss_rate", "pipelined_rps", "sync_rps",
                      "pipeline_speedup", "history_overhead_pct")),
    ("cold_start", ("cold_s", "replay_s", "disk_warm_s", "warm_s")),
    ("batched_cg", ("speedup_warm",)),
    ("fleet_batched_cg", ("speedup_warm",)),
    ("precond_cg", ("end_to_end_s", "iters_mean", "build_s", "speedup")),
    ("mixed_cg", ("exact_s", "f32ir_s", "bf16ir_s", "speedup",
                  "bytes_ratio_bf16")),
    ("auto_cg", ("regret_worst", "ill_speedup_vs_global")),
    ("ingest", ("sort_rows_per_s", "cold_onboard_ms", "dedup_onboard_ms",
                "dedup_speedup", "dedup_plan_misses")),
    ("remesh", ("shrink_cold_s", "shrink_warm_s", "shrink_warm_replan_ms",
                "shrink_warm_misses", "replayed")),
)


def _trend_round(path: str) -> dict:
    """One committed round artifact (``BENCH_rNN.json``) as a trend row:
    the ``parsed`` headline metric plus every embedded bench row
    recoverable from the run's stdout tail (the worker prints its record
    dict as JSON lines; the last line carrying each embed wins)."""
    try:
        data = json.load(open(path))
    except (OSError, json.JSONDecodeError):
        return {}
    if not isinstance(data, dict):
        return {}
    row: dict = {"file": os.path.basename(path)}
    if _num(data.get("n")) is not None:
        row["round"] = data["n"]
    parsed = data.get("parsed")
    if isinstance(parsed, dict) and _num(parsed.get("value")) is not None:
        row["metric"] = parsed.get("metric")
        if str(parsed.get("metric", "")).startswith("cg_iters_per_s"):
            row["cg_iters_per_s"] = parsed["value"]
    for line in str(data.get("tail") or "").splitlines():
        line = line.strip()
        if not (line.startswith("{") and line.endswith("}")):
            continue
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if not isinstance(rec, dict):
            continue
        for embed, keys in _TREND_EMBEDS:
            sub = rec.get(embed)
            if isinstance(sub, dict):
                picked = {k: sub[k] for k in keys if _num(sub.get(k))
                          is not None}
                if picked:
                    row[embed] = picked
    return row


def build_trend(paths) -> dict:
    """The cross-round trend table: one row per ``BENCH_r*.json``
    (sorted by filename = round order). This is the machine-generated
    form of ROADMAP's hand-copied bench trajectory — the headline
    ``cg_iters_per_s`` plus the serving rows (``sustained_cg`` req/s,
    ``cold_start`` restart times, batched/fleet speedups) per round."""
    rows = [r for r in (_trend_round(p) for p in sorted(paths)) if r]
    trend: dict = {"rounds": rows}
    series: dict = {}
    for r in rows:
        if _num(r.get("cg_iters_per_s")) is not None:
            series.setdefault("cg_iters_per_s", []).append(
                [r["file"], r["cg_iters_per_s"]]
            )
        for embed, keys in _TREND_EMBEDS:
            sub = r.get(embed)
            if isinstance(sub, dict):
                for k in keys:
                    if _num(sub.get(k)) is not None:
                        series.setdefault(f"{embed}.{k}", []).append(
                            [r["file"], sub[k]]
                        )
    trend["series"] = series
    return trend


def _print_trend(trend: dict) -> None:
    rows = trend.get("rounds", [])
    print(f"axon_report --trend: {len(rows)} bench round(s)")
    if not rows:
        return
    print(
        f"  {'round':<16} {'cg_iters/s':>10} {'sust req/s':>10} "
        f"{'p95 ms':>8} {'cold_s':>8} {'warm_s':>8}"
    )
    for r in rows:
        sc = r.get("sustained_cg") or {}
        cs = r.get("cold_start") or {}

        def cell(v, nd=2):
            return f"{v:.{nd}f}" if _num(v) is not None else "-"

        print(
            f"  {r['file']:<16} {cell(r.get('cg_iters_per_s')):>10} "
            f"{cell(sc.get('achieved_rps')):>10} "
            f"{cell(sc.get('p95_ms')):>8} {cell(cs.get('cold_s'), 3):>8} "
            f"{cell(cs.get('warm_s'), 3):>8}"
        )
    for name, pts in sorted(trend.get("series", {}).items()):
        if len(pts) >= 2:
            first, last = pts[0][1], pts[-1][1]
            delta = (
                f"{(last - first) / abs(first) * 100.0:+.1f}%"
                if first else "n/a"
            )
            print(
                f"  trend {name}: {first} -> {last} ({delta} over "
                f"{len(pts)} round(s))"
            )


# ---------------------------------------------------------------------------
# history join (Axon v7): segments across restarts -> incident window
# ---------------------------------------------------------------------------
def build_history(root: str) -> dict:
    """Join every committed history segment under ``root`` (the v7
    sampler's on-disk tier — ``scripts/axon_dash.py`` owns the stdlib
    segment parser) into one cross-restart summary: per-session spans
    plus the SLO-miss *incident window* — the interval over which the
    ``batch.slo_misses`` counter was actually moving."""
    sys.path.insert(0, HERE)
    import axon_dash

    points = axon_dash.read_segments(root, res=0)
    out: dict = {"root": root, "points": len(points)}
    if not points:
        return out
    sessions: dict = {}
    miss_series = []
    for p in points:
        s = sessions.setdefault(str(p.get("session")), {
            "first": p["t"], "last": p["t"], "points": 0,
        })
        s["first"] = min(s["first"], p["t"])
        s["last"] = max(s["last"], p["t"])
        s["points"] += 1
        v = (p.get("s") or {}).get("batch.slo_misses")
        if isinstance(v, (int, float)):
            miss_series.append((p["t"], v))
    out["sessions"] = sessions
    out["span_s"] = round(points[-1]["t"] - points[0]["t"], 3)
    # the incident window: first and last instants the miss counter
    # moved (per session — counters reset at process restart, so only
    # same-session deltas count as movement)
    incident = None
    prev = {}
    by_session: dict = {}
    for p in points:
        v = (p.get("s") or {}).get("batch.slo_misses")
        if not isinstance(v, (int, float)):
            continue
        sess = str(p.get("session"))
        last = prev.get(sess)
        prev[sess] = v
        if last is None or v <= last:
            continue
        if incident is None:
            incident = {"start": p["t"], "end": p["t"], "misses": 0}
        incident["end"] = p["t"]
        incident["misses"] += v - last
        by_session[sess] = by_session.get(sess, 0) + (v - last)
    if incident:
        incident["duration_s"] = round(
            incident["end"] - incident["start"], 3
        )
        incident["misses"] = round(incident["misses"], 3)
        if by_session:
            incident["by_session"] = by_session
        out["incident"] = incident
    return out


def _print_history(h: dict) -> None:
    print(f"axon_report --history: {h['root']} — {h['points']} point(s)")
    if not h["points"]:
        print("  (no segments — is SPARSE_TPU_HISTORY set?)")
        return
    print(f"  span {h['span_s']}s across {len(h['sessions'])} session(s):")
    for name, s in sorted(h["sessions"].items(),
                          key=lambda kv: kv[1]["first"]):
        print(
            f"    {name:<20} {s['points']:>6} point(s)  "
            + time.strftime("%H:%M:%S", time.localtime(s["first"]))
            + " -> "
            + time.strftime("%H:%M:%S", time.localtime(s["last"]))
        )
    inc = h.get("incident")
    if inc:
        print(
            "  incident window: "
            + time.strftime("%H:%M:%S", time.localtime(inc["start"]))
            + " -> "
            + time.strftime("%H:%M:%S", time.localtime(inc["end"]))
            + f" ({inc['duration_s']}s, {inc['misses']} SLO miss(es))"
        )
    else:
        print("  no SLO misses in the recorded window")


# ---------------------------------------------------------------------------
# comparison
# ---------------------------------------------------------------------------
def compare(current: dict, baseline: dict, threshold: float = 0.2) -> list:
    """Regressions of ``current`` vs ``baseline`` (both report dicts):
    metrics present in both whose value moved AGAINST its direction by
    more than ``threshold`` relative. Returns
    ``[{metric, base, cur, delta_pct}, ...]``; improvements and new /
    vanished metrics are never regressions."""
    regressions = []
    cur_m = current.get("metrics", {})
    base_m = baseline.get("metrics", {})
    for name in sorted(set(cur_m) & set(base_m)):
        cv, bv = _num(cur_m[name].get("v")), _num(base_m[name].get("v"))
        if cv is None or bv is None or bv == 0:
            continue
        hib = bool(cur_m[name].get("hib"))
        rel = (cv - bv) / abs(bv)
        worse = -rel if hib else rel
        if worse > threshold:
            regressions.append({
                "metric": name,
                "base": bv,
                "cur": cv,
                "delta_pct": round(rel * 100.0, 1),
            })
    return regressions


def informational(current: dict, baseline: dict) -> dict:
    """Metrics present on only one side of a comparison (ISSUE 11
    satellite): a baseline written before a new bench row exists (e.g.
    ``sustained_cg.*``) must not make ``--compare`` asymmetric — such
    metrics are LISTED, never gated. ``new`` = in current only (a row
    the baseline predates), ``vanished`` = in baseline only (a row this
    run failed to produce — worth a look, still not a regression)."""
    cur_m = set(current.get("metrics", {}))
    base_m = set(baseline.get("metrics", {}))
    return {
        "new": sorted(cur_m - base_m),
        "vanished": sorted(base_m - cur_m),
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _print_report(rep: dict) -> None:
    print(f"axon_report: {rep['records']} — {rep['events_total']} events")
    if rep["events_by_kind"]:
        print("  events by kind:")
        for k, n in rep["events_by_kind"].items():
            print(f"    {k:<22} {n}")
    if rep["spans"]:
        print("  spans (p50/p95/max seconds):")
        for name, st in rep["spans"].items():
            print(
                f"    {name:<28} n={st['n']:<6} p50={st['p50_s']:.6f} "
                f"p95={st['p95_s']:.6f} max={st['max_s']:.6f}"
            )
    if rep["solvers"]:
        print("  solvers:")
        for name, s in rep["solvers"].items():
            print(
                f"    {name:<12} solves={s['solves']:<5} "
                f"iters_mean={s['iters_mean']:<8} paths={s['paths']}"
            )
    if rep["comm_bytes"]:
        print("  comm volumes (structural bytes):")
        for k, b in rep["comm_bytes"].items():
            print(f"    {k:<22} {b}")
    if rep.get("comm"):
        print("  measured comm (vs analytic model):")
        for site, s in sorted(rep["comm"].items()):
            bits = [f"measured={s['measured_bytes']}"]
            if s.get("model_bytes"):
                bits.append(f"model={s['model_bytes']}")
            if s.get("divergence_pct") is not None:
                bits.append(f"div={s['divergence_pct']:+.2f}%")
            if s.get("achieved_gbs_per_shard") is not None:
                bits.append(f"{s['achieved_gbs_per_shard']}GB/s/shard")
            if s.get("pct_peak_ici") is not None:
                bits.append(f"{s['pct_peak_ici']}%ICI")
            if not s.get("exact"):
                bits.append("(capacity-bounded)")
            print(f"    {site:<22} " + " ".join(bits))
    if rep["cache"]["session"]:
        c = rep["cache"]["session"]
        print(
            f"  plan cache: hits={c.get('hits')} misses={c.get('misses')} "
            f"hit_rate={c.get('hit_rate', 0):.4f}"
        )
    if rep["anomalies"]:
        print(f"  anomalies ({len(rep['anomalies'])}):")
        for a in rep["anomalies"][:10]:
            print(
                f"    {a.get('solver', '?'):<10} {a.get('reason', '?'):<12}"
                f" iter={a.get('iter')} lane={a.get('lane')}"
            )
    tk = rep.get("tickets") or {}
    if tk.get("n"):
        lat = tk["latency_ms"]
        print(
            f"  tickets: n={tk['n']} states={tk['states']} "
            f"requeued={tk['requeued']} slo_misses={tk['slo_misses']}"
        )
        print(
            f"    latency_ms p50={lat['p50']} p95={lat['p95']} "
            f"p99={lat['p99']} max={lat['max']}"
        )
        if tk.get("phase_ms_mean"):
            ph = tk["phase_ms_mean"]
            print(
                "    phase mean (ms): "
                + " ".join(
                    f"{p}={ph[p]}" for p in _TICKET_PHASES if p in ph
                )
            )
    usage = rep.get("usage") or {}
    if usage.get("tenants") or usage.get("device_ms_total"):
        bits = []
        if usage.get("device_ms_total") is not None:
            bits.append(f"sampled device_ms={usage['device_ms_total']}")
        print("  usage (per-tenant metering)"
              + (": " + " ".join(bits) if bits else ":"))
        for tenant, r in sorted((usage.get("tenants") or {}).items()):
            cols = " ".join(f"{k}={v}" for k, v in sorted(r.items()))
            print(f"    {tenant or '(default)':<14} {cols}")
    budget = rep.get("budget") or {}
    if budget:
        print(
            f"  budget (objective {budget.get('objective')}): "
            f"fast_burn_max={budget.get('fast_burn_max')} "
            f"slow_burn_max={budget.get('slow_burn_max')}"
        )
        for tenant, r in sorted((budget.get("tenants") or {}).items()):
            if tenant == "":
                continue  # the aggregate is the headline line above
            print(
                f"    tenant {tenant:<12} tickets={r['tickets']} "
                f"misses={r['misses']} fast={r['fast_burn_max']} "
                f"slow={r['slow_burn_max']}"
            )
    load = rep.get("load") or {}
    if load.get("runs"):
        ll = load["last"]
        print(
            f"  load ({load['runs']} run(s); last trace "
            f"{ll.get('trace', '?')!r}):"
        )
        print(
            f"    offered={ll.get('offered_rps')}req/s "
            f"achieved={ll.get('achieved_rps')}req/s "
            f"p50={ll.get('p50_ms')}ms p95={ll.get('p95_ms')}ms "
            f"p99={ll.get('p99_ms')}ms "
            f"slo_miss_rate={ll.get('slo_miss_rate')} "
            f"fairness={ll.get('fairness')}"
        )
        for tenant, t in sorted((ll.get("tenants") or {}).items()):
            print(
                f"    tenant {tenant or '(default)':<12} "
                f"completed={t.get('completed')} "
                f"achieved={t.get('achieved_rps')}req/s "
                f"weight={t.get('weight')}"
            )
    al = rep.get("alerts") or {}
    if al.get("fired") or al.get("cleared"):
        print(
            f"  watchdog alerts: fired={al['fired']} "
            f"cleared={al['cleared']}"
            + (f" UNRESOLVED={al['unresolved']}" if al["unresolved"]
               else "")
        )
        for rule, r in sorted(al.get("by_rule", {}).items()):
            print(
                f"    {rule:<20} fired={r['fired']} "
                f"cleared={r['cleared']} severity={r.get('severity')}"
            )
    srow = rep.get("sustained_row")
    if srow:
        print(
            "  sustained_cg: "
            f"offered={srow.get('offered_rps')}req/s "
            f"achieved={srow.get('achieved_rps')}req/s "
            f"p95={srow.get('p95_ms')}ms (slo {srow.get('slo_ms')}ms) "
            f"slo_miss_rate={srow.get('slo_miss_rate')}"
        )
        if srow.get("pipeline_speedup") is not None:
            print(
                "    pipeline: "
                f"on={srow.get('pipelined_rps')}req/s "
                f"off={srow.get('sync_rps')}req/s "
                f"speedup={srow.get('pipeline_speedup')}x "
                f"(inflight={srow.get('inflight')}, "
                f"host_cores={srow.get('host_cores')})"
            )
    prow = rep.get("precond_row")
    if prow:
        print(
            "  precond_cg: "
            f"{prow.get('best_kind')} {prow.get('end_to_end_s')}s "
            f"vs none {(prow.get('none') or {}).get('end_to_end_s')}s "
            f"(speedup={prow.get('speedup')}x, "
            f"iters {(prow.get('none') or {}).get('iters_mean')} -> "
            f"{prow.get('iters_mean')}, build={prow.get('build_s')}s, "
            f"profile={prow.get('profile')})"
        )
    mrow = rep.get("mixed_row")
    if mrow:
        print(
            "  mixed_cg: "
            f"f32ir {mrow.get('f32ir_s')}s vs exact "
            f"{mrow.get('exact_s')}s (speedup={mrow.get('speedup')}x, "
            f"bf16ir {mrow.get('bf16ir_s')}s, values-bytes f64/bf16="
            f"{mrow.get('bytes_ratio_bf16')}x, "
            f"profile={mrow.get('profile')})"
        )
    arow = rep.get("auto_row")
    if arow:
        print(
            "  auto_cg: worst regret vs best static "
            f"{arow.get('regret_worst')}, "
            f"{arow.get('ill_speedup_vs_global')}x vs the global default "
            f"on pde_ill (win={arow.get('win')})"
        )
    irow = rep.get("ingest_row")
    if irow:
        print(
            "  ingest: "
            f"sort {irow.get('sort_rows_per_s')}rows/s "
            f"({irow.get('shards')} shard(s)), cold onboard "
            f"{irow.get('cold_onboard_ms')}ms vs dedup "
            f"{irow.get('dedup_onboard_ms')}ms "
            f"(speedup={irow.get('dedup_speedup')}x, dedup plan misses="
            f"{irow.get('dedup_plan_misses')}, win={irow.get('win')})"
        )
    auto = rep.get("autopilot") or {}
    if auto.get("n_groups"):
        print(
            f"  autopilot: {auto['converged']}/{auto['n_groups']} "
            f"group(s) converged, {auto['trials']} trial(s), "
            f"{auto['reopens']} reopen(s), {auto['aborts']} "
            "SLO abort(s)"
        )
        for gid, g in sorted(auto["groups"].items()):
            chosen = g.get("chosen") or "exploring"
            print(
                f"    {gid:<36} {chosen} "
                f"[{g.get('source') or '-'}] trials={g['trials']} "
                f"score={g.get('chosen_score_ms')}ms "
                f"regret={g.get('regret')}"
            )
    progs = rep.get("programs") or {}
    if progs:
        print(
            f"  programs ({len(progs)}; cold start "
            f"{rep.get('cold_start_s', 0)}s compile+pack):"
        )
        for key, p in sorted(progs.items()):
            bits = [f"solves={p.get('solves', 0)}"]
            for f, fmt in (
                ("compile_s", "compile={}s"), ("flops", "flops={:.3g}"),
                ("bytes", "bytes={:.3g}"),
                ("achieved_gflops", "achieved={}GF/s"),
                ("achieved_gbs", "{}GB/s"),
                ("pct_peak_gflops", "{}%peakF"),
                ("pct_peak_gbs", "{}%peakB"),
                ("flops_per_byte", "AI={}"),
                # measured device time (sampled timed dispatches)
                ("device_ms_mean", "dev={}ms"),
                ("host_ms_mean", "host={}ms"),
                ("device_samples", "x{}sampled"),
                ("achieved_gflops_dev", "dev_achieved={}GF/s"),
            ):
                v = p.get(f)
                if v is not None:
                    bits.append(fmt.format(v))
            print(f"    {key:<30} " + " ".join(bits))
    if rep["bench"]:
        print("  bench metrics:")
        seen = set()
        for row in rep["bench"]:
            if row["metric"] in seen:
                continue
            seen.add(row["metric"])
            print(
                f"    {row['metric']:<34} {row['value']} {row['unit'] or ''}"
                f"  [{row['source']}]"
            )


def main(argv) -> int:
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    args = list(argv)
    quiet = "--quiet" in args
    if quiet:
        args.remove("--quiet")

    def take(flag, default=None, many=False):
        vals = []
        while flag in args:
            i = args.index(flag)
            if i + 1 >= len(args):
                print(f"axon_report: {flag} needs a value", file=sys.stderr)
                raise SystemExit(2)
            vals.append(args[i + 1])
            del args[i:i + 2]
        if many:
            return vals
        return vals[-1] if vals else default

    bench_args = take("--bench", many=True)
    out_json = take("--json")
    baseline_path = take("--compare")
    # --trend (ISSUE 12 satellite): the cross-round bench table, no
    # session log needed — positional args become BENCH_r*.json globs
    if "--trend" in args:
        args.remove("--trend")
        pats = args or [os.path.join(REPO, "BENCH_r*.json")]
        paths = []
        for pat in pats:
            hits = sorted(_glob.glob(pat))
            paths.extend(hits if hits else [pat])
        trend = build_trend(paths)
        if not quiet:
            _print_trend(trend)
        if out_json:
            os.makedirs(
                os.path.dirname(os.path.abspath(out_json)), exist_ok=True
            )
            with open(out_json, "w") as f:
                json.dump(trend, f, indent=1, sort_keys=True)
                f.write("\n")
            if not quiet:
                print(f"  trend -> {out_json}")
        return 0 if trend["rounds"] else 2
    # --history (ISSUE 19): join the v7 on-disk history segments across
    # restarts — positional arg is the segments dir
    if "--history" in args:
        args.remove("--history")
        hist = build_history(args[0] if args else DEFAULT_HISTORY)
        if not quiet:
            _print_history(hist)
        if out_json:
            os.makedirs(
                os.path.dirname(os.path.abspath(out_json)), exist_ok=True
            )
            with open(out_json, "w") as f:
                json.dump(hist, f, indent=1, sort_keys=True)
                f.write("\n")
            if not quiet:
                print(f"  history -> {out_json}")
        return 0 if hist["points"] else 2
    try:
        threshold = float(take("--threshold", "0.2"))
        pk_gf = take("--peak-gflops")
        peak_gflops = float(pk_gf) if pk_gf is not None else None
        pk_gb = take("--peak-gbs")
        peak_gbs = float(pk_gb) if pk_gb is not None else None
        pk_ici = take("--peak-ici-gbs")
        peak_ici_gbs = float(pk_ici) if pk_ici is not None else None
    except ValueError:
        print("axon_report: --threshold/--peak-* must be numbers",
              file=sys.stderr)
        return 2
    records = args[0] if args else DEFAULT_RECORDS
    if not os.path.exists(records):
        print(f"axon_report: no session log at {records}", file=sys.stderr)
        return 2

    bench_paths = []
    for pat in bench_args:
        hits = sorted(_glob.glob(pat))
        bench_paths.extend(hits if hits else [pat])

    rep = build_report(records, bench_paths, peak_gflops=peak_gflops,
                       peak_gbs=peak_gbs, peak_ici_gbs=peak_ici_gbs)
    if not quiet:
        _print_report(rep)
    if out_json:
        d = os.path.dirname(os.path.abspath(out_json))
        os.makedirs(d, exist_ok=True)
        with open(out_json, "w") as f:
            json.dump(rep, f, indent=1, sort_keys=True)
            f.write("\n")
        if not quiet:
            print(f"  report -> {out_json}")

    if baseline_path:
        try:
            baseline = json.load(open(baseline_path))
        except (OSError, json.JSONDecodeError) as e:
            print(f"axon_report: bad baseline {baseline_path}: {e}",
                  file=sys.stderr)
            return 2
        regs = compare(rep, baseline, threshold)
        info = informational(rep, baseline)
        if not quiet:
            # one-sided metrics are informational by contract: a
            # baseline predating a new bench row never gates, and a
            # vanished row is surfaced without failing the run
            if info["new"]:
                print(
                    f"  {len(info['new'])} metric(s) not in baseline "
                    "(informational): " + ", ".join(info["new"][:8])
                    + (" ..." if len(info["new"]) > 8 else "")
                )
            if info["vanished"]:
                print(
                    f"  {len(info['vanished'])} baseline metric(s) "
                    "missing from this run (informational): "
                    + ", ".join(info["vanished"][:8])
                    + (" ..." if len(info["vanished"]) > 8 else "")
                )
        if regs:
            print(
                f"axon_report: {len(regs)} regression(s) vs "
                f"{os.path.basename(baseline_path)} "
                f"(threshold {threshold:.0%}):",
                file=sys.stderr,
            )
            for r in regs:
                print(
                    f"  REGRESSION {r['metric']}: {r['base']} -> {r['cur']} "
                    f"({r['delta_pct']:+.1f}%)",
                    file=sys.stderr,
                )
            return 1
        if not quiet:
            print(
                f"  no regressions vs {os.path.basename(baseline_path)} "
                f"(threshold {threshold:.0%})"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
