"""f32-vs-f64-vs-IR accuracy oracle for the PDE/CG headline (VERDICT r2 #6).

The headline benchmark runs the 6000^2 5-point Poisson CG in f32 on TPU and
compares throughput against the reference's f64 V100 number. This script
quantifies what the dtype asymmetry costs in ACCURACY: it runs the identical
300-iteration CG (the same `models.poisson` step the bench times) in both
dtypes on CPU and reports, per grid size:

  - true relative residual ||b - A x_300|| / ||b|| for f32 and f64
  - relative iterate distance ||x_f32 - x_300_f64|| / ||x_f64||
  - relative error vs the sampled ground-truth xtrue for both
  - the MIXED-PRECISION columns (ISSUE 15): the `ir` solver — f32 (and
    bf16-storage) inner Krylov sweeps under the f64 iterative-refinement
    outer loop (sparse_tpu.mixed) — driven to the SAME absolute residual
    target the plain f64 run achieved, with its refinement sweep count.
    This is the pinned oracle for the serving stack's `f32ir`/`bf16ir`
    dtype policies: reduced-precision storage, f64-verified accuracy.

The fused Pallas CG used for the TPU headline computes the same recurrence as
this step loop (residual parity asserted in tests/test_cg_fused.py and
measured identical at 6000^2 on hardware, BENCH_NOTES.md r2 sweep: rho
0.001092 for both), so the step loop stands in for it here.

``tests/test_mixed.py`` imports :func:`run` and pins the per-size table's
accuracy claims in CI (the satellite contract: the table lives in a test
fixture, not just BENCH_NOTES.md).

Usage: python scripts/f64_oracle.py [n ...]   (default: 512 2000 6000)
Prints one JSON line per size; paste the table into BENCH_NOTES.md.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from sparse_tpu.models.poisson import cg_dia, poisson_cg_state_dia
from sparse_tpu.ops.dia_spmv import dia_spmv_xla

ITERS = 300


def run(n: int, ir_policies=("f32ir", "bf16ir")) -> dict:
    N = n * n
    offsets = (-n, -1, 0, 1, n)
    out = {"n": n, "iters": ITERS}
    sols = {}
    # ONE problem, built in f64 (jax.random draws different streams per
    # dtype, so the f32 run must downcast this b — not resample it)
    state64, step = poisson_cg_state_dia(n, dtype=jnp.float64)
    planes64, _, b64, _, _ = state64
    xtrue = jax.random.normal(jax.random.PRNGKey(0), (N,), dtype=jnp.float64)
    for dtype in (jnp.float64, jnp.float32):
        planes = planes64.astype(dtype)
        b = b64.astype(dtype)
        zero_v = jnp.zeros((N,), dtype=dtype)
        zero_s = jnp.zeros((), dtype=dtype)
        x, r, p, rho = cg_dia(step, planes, zero_v, b, zero_v, zero_s, iters=ITERS)
        # residual and norms evaluated in f64 regardless of solve dtype
        x64 = x.astype(jnp.float64)
        resid = dia_spmv_xla(planes64, offsets, x64, (N, N)) - b64
        rel_resid = float(jnp.linalg.norm(resid) / jnp.linalg.norm(b64))
        xerr = float(jnp.linalg.norm(x64 - xtrue) / jnp.linalg.norm(xtrue))
        tag = "f64" if dtype == jnp.float64 else "f32"
        out[f"rel_resid_{tag}"] = rel_resid
        out[f"rel_err_vs_xtrue_{tag}"] = xerr
        sols[tag] = np.asarray(x64)
    out["rel_iterate_dist_f32_vs_f64"] = float(
        np.linalg.norm(sols["f32"] - sols["f64"]) / np.linalg.norm(sols["f64"])
    )

    # the IR columns (ISSUE 15): drive the mixed-precision solver to the
    # SAME absolute residual the plain f64 run achieved — matching
    # achieved tolerance, reduced-precision inner sweeps
    from sparse_tpu.mixed import ir_solve

    bnorm = float(jnp.linalg.norm(b64))
    target = max(out["rel_resid_f64"], 1e-14) * bnorm

    def mk(planes):
        def mv(X):
            return jax.vmap(
                lambda v: dia_spmv_xla(planes, offsets, v, (N, N))
            )(X)

        return mv

    for policy in ir_policies:
        low_dt = jnp.float32 if policy == "f32ir" else jnp.bfloat16
        x_ir, info = ir_solve(
            (mk(planes64), mk(planes64.astype(low_dt))), b64,
            tol=target, maxiter=6 * ITERS, policy=policy,
        )
        resid = dia_spmv_xla(planes64, offsets, x_ir.astype(jnp.float64),
                             (N, N)) - b64
        out[f"rel_resid_{policy}"] = float(
            jnp.linalg.norm(resid) / jnp.linalg.norm(b64)
        )
        out[f"{policy}_converged"] = bool(np.asarray(info.converged).all())
        out[f"{policy}_inner_iters"] = int(np.asarray(info.iters).max())
        out[f"{policy}_outer"] = int(info.outer)
    return out


if __name__ == "__main__":
    sizes = [int(a) for a in sys.argv[1:]] or [512, 2000, 6000]
    for n in sizes:
        print(json.dumps(run(n)))
        sys.stdout.flush()
