"""f32-vs-f64 accuracy comparison for the PDE/CG headline (VERDICT r2 #6).

The headline benchmark runs the 6000^2 5-point Poisson CG in f32 on TPU and
compares throughput against the reference's f64 V100 number. This script
quantifies what the dtype asymmetry costs in ACCURACY: it runs the identical
300-iteration CG (the same `models.poisson` step the bench times) in both
dtypes on CPU and reports, per grid size:

  - true relative residual ||b - A x_300|| / ||b|| for f32 and f64
  - relative iterate distance ||x_f32 - x_300_f64|| / ||x_f64||
  - relative error vs the sampled ground-truth xtrue for both

The fused Pallas CG used for the TPU headline computes the same recurrence as
this step loop (residual parity asserted in tests/test_cg_fused.py and
measured identical at 6000^2 on hardware, BENCH_NOTES.md r2 sweep: rho
0.001092 for both), so the step loop stands in for it here.

Usage: python scripts/f64_oracle.py [n ...]   (default: 512 2000 6000)
Prints one JSON line per size; paste the table into BENCH_NOTES.md.
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("PALLAS_AXON_POOL_IPS", None)

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

from sparse_tpu.models.poisson import cg_dia, poisson_cg_state_dia
from sparse_tpu.ops.dia_spmv import dia_spmv_xla

ITERS = 300


def run(n: int) -> dict:
    N = n * n
    offsets = (-n, -1, 0, 1, n)
    out = {"n": n, "iters": ITERS}
    sols = {}
    # ONE problem, built in f64 (jax.random draws different streams per
    # dtype, so the f32 run must downcast this b — not resample it)
    state64, step = poisson_cg_state_dia(n, dtype=jnp.float64)
    planes64, _, b64, _, _ = state64
    xtrue = jax.random.normal(jax.random.PRNGKey(0), (N,), dtype=jnp.float64)
    for dtype in (jnp.float64, jnp.float32):
        planes = planes64.astype(dtype)
        b = b64.astype(dtype)
        zero_v = jnp.zeros((N,), dtype=dtype)
        zero_s = jnp.zeros((), dtype=dtype)
        x, r, p, rho = cg_dia(step, planes, zero_v, b, zero_v, zero_s, iters=ITERS)
        # residual and norms evaluated in f64 regardless of solve dtype
        x64 = x.astype(jnp.float64)
        resid = dia_spmv_xla(planes64, offsets, x64, (N, N)) - b64
        rel_resid = float(jnp.linalg.norm(resid) / jnp.linalg.norm(b64))
        xerr = float(jnp.linalg.norm(x64 - xtrue) / jnp.linalg.norm(xtrue))
        tag = "f64" if dtype == jnp.float64 else "f32"
        out[f"rel_resid_{tag}"] = rel_resid
        out[f"rel_err_vs_xtrue_{tag}"] = xerr
        sols[tag] = np.asarray(x64)
    out["rel_iterate_dist_f32_vs_f64"] = float(
        np.linalg.norm(sols["f32"] - sols["f64"]) / np.linalg.norm(sols["f64"])
    )
    return out


if __name__ == "__main__":
    sizes = [int(a) for a in sys.argv[1:]] or [512, 2000, 6000]
    for n in sizes:
        print(json.dumps(run(n)))
        sys.stdout.flush()
