#!/usr/bin/env python
"""Export a telemetry session log as Chrome-trace/Perfetto JSON.

Usage:
    python scripts/axon_trace.py [records.jsonl] [out.json]

Defaults: ``results/axon/records.jsonl`` -> ``results/axon/trace.json``.
Open the output in https://ui.perfetto.dev (or chrome://tracing) for
the timeline view — one process lane per subsystem (solver, kernels,
comm, plan_cache, batch, bench, spans, resilience, tickets), spans as
nested slices, ``resid2`` as a per-solver counter track, and one track
per serving ticket (``batch.ticket`` terminal events render as an
end-to-end slice containing the queue → pack → compile → solve →
readback phase breakdown) — docs/telemetry.md.

bench.py hardware metric records sharing the log (no ``kind`` field)
are skipped by contract; a trimmed/partial session exports fine.

Multi-controller sessions: merge the per-process ``records.<pid>.jsonl``
files first (``python scripts/axon_merge.py``) and point this script at
the merged log — events carrying more than one ``pi`` (process_index)
render each controller's subsystem lanes side by side under a ``p<pi>/``
prefix (``p0/comm``, ``p1/solver``, ...).
"""

import os
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_IN = os.path.join(REPO, "results", "axon", "records.jsonl")
DEFAULT_OUT = os.path.join(REPO, "results", "axon", "trace.json")


def main(argv) -> int:
    args = [a for a in argv if not a.startswith("-")]
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    src = args[0] if len(args) > 0 else DEFAULT_IN
    out = args[1] if len(args) > 1 else DEFAULT_OUT
    if not os.path.exists(src):
        print(f"axon_trace: no session log at {src}", file=sys.stderr)
        return 2

    sys.path.insert(0, REPO)
    from sparse_tpu.telemetry import _trace

    events = _trace.read_events_jsonl(src)
    if not events:
        print(f"axon_trace: {src} holds no telemetry events", file=sys.stderr)
        return 1
    _trace.export_trace(out, events=events)
    spans = sum(1 for e in events if e.get("kind") == "span")
    print(
        f"axon_trace: {len(events)} events ({spans} spans) -> {out}\n"
        "open in https://ui.perfetto.dev"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
