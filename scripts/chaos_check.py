"""Chaos gate (ISSUE 5): prove the stack survives injected failures.

Runs the quick chaos scenario under fixed seeds on the CPU backend and
exits nonzero if any solve fails to recover or the recovery telemetry
chains are missing:

1. **Unbatched recovery** — CG, BiCGStab and GMRES on an SPD tridiagonal
   system with ``nonfinite:matvec:p=0.01`` injection: every solver must
   converge to tol through the recovery policy engine
   (``sparse_tpu.resilience.policy``) within its attempt budget, and the
   session log must contain the full ``fault.injected -> solver.retry ->
   solver.recovered`` chain.
2. **Forced Pallas failure** — a ``fail:pallas`` clause against the SELL
   kernel: the result must stay correct through the XLA failover, a
   consistent ``kernel.failover`` event must be emitted, and the
   probe-based reinstate hook must clear the latch
   (``kernel.reinstate``).
3. **Batched recovery** — ``SolveSession.solve_many`` under the same
   matvec corruption: every lane converges (requeue allowed), with
   ``batch.dispatch`` events present.
4. **Checkpoint preemption** — ``checkpointed_cg`` under
   ``preempt:chunk`` injection: re-running after each preemption resumes
   from the checkpoint and finishes the solve.

Telemetry is pointed at a temp sink (never the committed
``results/axon/records.jsonl``). Wired into the quick lane through
``scripts/check_quick_lane.py``'s script-integrity list and exercised by
``tests/test_resilience.py``.

Usage:
    python scripts/chaos_check.py [--json]
"""

from __future__ import annotations

import json
import os
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the fixed chaos spec of scenarios 1/3 (seeded => bit-reproducible)
MATVEC_SPEC = "nonfinite:matvec:p=0.01,seed=7"
PREEMPT_SPEC = "preempt:chunk:p=0.25,seed=11,n=3"
N = 64
TOL = 1e-8
MAX_ATTEMPTS = 10


def _tridiag(n, seed=0):
    import numpy as np
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _event_kinds(tel):
    kinds: dict = {}
    for ev in tel.events():
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
    return kinds


def run(report: dict) -> list:
    """Run every scenario; returns a list of problem strings."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import sparse_tpu
    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.checkpoint import checkpointed_cg
    from sparse_tpu.config import settings
    from sparse_tpu.resilience import (
        RecoveryPolicy,
        failover,
        faults,
        solve_with_recovery,
    )

    problems = []
    S = _tridiag(N)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(1).standard_normal(N)

    # -- 1. unbatched recovery under matvec corruption ----------------------
    for solver in ("cg", "bicgstab", "gmres"):
        tel.reset()
        faults.clear()
        faults.configure(MATVEC_SPEC)
        try:
            x, info = solve_with_recovery(
                A, b, solver=solver, tol=TOL,
                policy=RecoveryPolicy(max_attempts=MAX_ATTEMPTS),
            )
        finally:
            faults.clear()
        rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
        kinds = _event_kinds(tel)
        fires = sum(faults.stats().values()) or kinds.get("fault.injected", 0)
        report[f"solver.{solver}"] = {
            "converged": bool(info.converged), "attempts": info.attempts,
            "rnorm": rnorm, "events": kinds,
        }
        target = TOL * max(float(np.linalg.norm(b)), 1.0) \
            if solver == "gmres" else TOL
        if not info.converged or rnorm > 10 * target:
            problems.append(
                f"{solver}: failed to recover (converged={info.converged}, "
                f"||r||={rnorm:.2e})"
            )
        if kinds.get("fault.injected", 0) == 0:
            problems.append(f"{solver}: no fault.injected events — the "
                            "chaos spec injected nothing")
        if info.attempts > 1 and kinds.get("solver.retry", 0) == 0:
            problems.append(f"{solver}: recovery ran without solver.retry "
                            "events")
        if info.recovered and kinds.get("solver.recovered", 0) == 0:
            problems.append(f"{solver}: missing solver.recovered event")

    # -- 2. forced Pallas failure + probe reinstate -------------------------
    tel.reset()
    faults.configure("fail:pallas:kernel=sell_spmv,n=1")
    old_mode = settings.spmv_mode
    try:
        from sparse_tpu.kernels.sell_spmv import PreparedCSR

        settings.spmv_mode = "pallas"
        G = _tridiag(32).astype(np.float32)
        prep = PreparedCSR(G.indptr, G.indices, G.data, G.shape)
        xs = np.random.default_rng(2).standard_normal(32).astype(np.float32)
        y = np.asarray(prep(xs))
        ok = np.allclose(y, G @ xs, rtol=1e-5, atol=1e-5)
        kinds = _event_kinds(tel)
        latched = failover.failed(prep.KERNEL, prep)
        faults.clear()
        reinstated = prep.probe_pallas(xs.astype(np.float32))
        report["pallas_failover"] = {
            "result_ok": bool(ok), "latched": bool(latched),
            "reinstated": bool(reinstated), "events": _event_kinds(tel),
        }
        if not ok:
            problems.append("pallas failover: XLA fallback result wrong")
        if not latched or kinds.get("kernel.failover", 0) == 0:
            problems.append("pallas failover: no kernel.failover latch/event")
        if not reinstated or failover.failed(prep.KERNEL, prep):
            problems.append("pallas failover: probe did not reinstate")
    finally:
        settings.spmv_mode = old_mode
        faults.clear()

    # -- 3. batched recovery ------------------------------------------------
    tel.reset()
    faults.configure(MATVEC_SPEC)
    try:
        rng = np.random.default_rng(3)
        mats = []
        for _ in range(4):
            M = _tridiag(N)
            M.setdiag(3.0 + rng.random(N))
            mats.append(M.tocsr())
        rhs = rng.standard_normal((4, N))
        sess = SolveSession("cg")
        X, iters, resid2 = sess.solve_many(mats, rhs, tol=TOL)
    finally:
        faults.clear()
    lane_resids = [
        float(np.linalg.norm(m @ x - r)) for m, x, r in zip(mats, X, rhs)
    ]
    kinds = _event_kinds(tel)
    report["batch"] = {"lane_resids": lane_resids, "events": kinds}
    bad = [r for r in lane_resids if not (r <= 10 * TOL)]
    if bad:
        problems.append(f"batch: {len(bad)} lanes failed to recover "
                        f"(worst ||r||={max(bad):.2e})")
    if kinds.get("batch.dispatch", 0) == 0:
        problems.append("batch: no batch.dispatch events")

    # -- 4. preemption + checkpoint resume ----------------------------------
    tel.reset()
    faults.configure(PREEMPT_SPEC)
    ck = os.path.join(tempfile.mkdtemp(prefix="chaos_ck_"), "cg.npz")
    x = None
    resumes = 0
    try:
        for _ in range(8):  # preempt budget n=3 bounds this
            try:
                x, _ = checkpointed_cg(A, b, ck, tol=TOL, chunk=20)
                break
            except faults.Preempted:
                resumes += 1
        else:
            problems.append("preempt: solve never completed")
    finally:
        faults.clear()
    if x is not None:
        rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
        report["preempt"] = {"resumes": resumes, "rnorm": rnorm}
        if rnorm > 10 * TOL:
            problems.append(f"preempt: resumed solve wrong (||r||={rnorm:.2e})")
        if resumes == 0:
            problems.append("preempt: injection never fired (spec drift?)")
    return problems


def main(argv) -> int:
    report: dict = {}
    from sparse_tpu import telemetry as tel
    from sparse_tpu.config import settings

    old_tel = settings.telemetry
    sink = tempfile.NamedTemporaryFile(
        suffix=".jsonl", prefix="chaos_", delete=False
    )
    sink.close()
    settings.telemetry = True
    tel.configure(sink.name)
    try:
        problems = run(report)
    finally:
        settings.telemetry = old_tel
        tel.configure(None)
        tel.reset()
        try:
            os.unlink(sink.name)
        except OSError:
            pass
    if "--json" in argv:
        print(json.dumps(report, indent=1, default=str))
    for p in problems:
        print(f"CHAOS FAILURE: {p}", file=sys.stderr)
    if not problems:
        print(
            "chaos check passed: "
            f"{len([k for k in report if k.startswith('solver.')])} solvers "
            "recovered, pallas failover+reinstate ok, "
            f"batch lanes ok, {report.get('preempt', {}).get('resumes', 0)} "
            "preemption resume(s)"
        )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
