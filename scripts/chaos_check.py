"""Chaos gate (ISSUE 5): prove the stack survives injected failures.

Runs the quick chaos scenario under fixed seeds on the CPU backend and
exits nonzero if any solve fails to recover or the recovery telemetry
chains are missing:

1. **Unbatched recovery** — CG, BiCGStab and GMRES on an SPD tridiagonal
   system with ``nonfinite:matvec:p=0.01`` injection: every solver must
   converge to tol through the recovery policy engine
   (``sparse_tpu.resilience.policy``) within its attempt budget, and the
   session log must contain the full ``fault.injected -> solver.retry ->
   solver.recovered`` chain.
2. **Forced Pallas failure** — a ``fail:pallas`` clause against the SELL
   kernel: the result must stay correct through the XLA failover, a
   consistent ``kernel.failover`` event must be emitted, and the
   probe-based reinstate hook must clear the latch
   (``kernel.reinstate``).
3. **Batched recovery** — ``SolveSession.solve_many`` under the same
   matvec corruption: every lane converges (requeue allowed), with
   ``batch.dispatch`` events present.
4. **Checkpoint preemption** — ``checkpointed_cg`` under
   ``preempt:chunk`` injection: re-running after each preemption resumes
   from the checkpoint and finishes the solve.
5. **Vault io chaos** (ISSUE 9) — ``io:*`` fault clauses against the
   persistent plan-cache tier: a bitflipped read, a truncated write, a
   stale-format artifact and an injected ENOSPC must each degrade to
   quarantine + rebuild (``vault.quarantined`` / ``vault.write_failed``
   evidence, ``vault.quarantine`` events) with the rebuilt pack
   identical — no crash, no wrong layout.
6. **Kill-and-restart** (ISSUE 9 acceptance drill) — a subprocess
   serving ``SolveSession`` traffic over a vault SIGKILLs itself
   mid-traffic; a fresh process replays the warm-start manifest and
   serves the same bucket set with ZERO plan-cache misses in the
   serving window (disk-tier hits only), all lanes converged.
7. **Fleet kill-and-restart** (ISSUE 10 acceptance drill) — the same
   drill under ``SPARSE_TPU_FLEET=auto`` on the forced 8-device virtual
   CPU mesh: the serving child builds mesh-SHARDED bucket programs (the
   manifest entries carry the mesh fingerprint), and the fresh process
   replays the mesh-keyed manifest back to a zero-serving-miss window —
   proving warm restarts survive under distributed serving, not just
   single-device.
8. **Loadgen + watchdog alerting** (ISSUE 11 acceptance drill) — a
   seeded Poisson trace (``sparse_tpu.loadgen``) drives a warm
   ``SolveSession`` while a ``delay:dispatch`` fault clause inflates
   every dispatch past the session's ``slo_ms``: the SLO watchdog
   (``telemetry/_watchdog.py``) must fire its ``slo_miss_rate`` alert
   DURING injection (``watchdog.alert`` event + always-on
   ``watchdog.alerts`` counter) and emit ``watchdog.clear`` after the
   faults lift and clean traffic flows — alerting proven end-to-end,
   not just unit-tested.
9. **Incident flight recorder + doctor** (ISSUE 12 acceptance drill) —
   the scenario-8 drill with the flight recorder enabled and sampled
   device profiling on: the ``slo_miss_rate`` alert during
   ``delay:dispatch`` injection must auto-capture EXACTLY ONE
   rate-limited postmortem bundle (a second alert inside the window is
   suppressed, never a second bundle), the bundle must carry the ring
   tail with the ``fault.injected`` chain, sampled ``batch.dispatch``
   events must carry the measured ``device_ms`` split, and
   ``scripts/axon_doctor.py --json`` over the bundle must name
   "injected dispatch delay" as the probable cause — the alert →
   evidence → diagnosis loop proven end-to-end.
10. **Streaming pipeline restart + admission control** (ISSUE 13
   acceptance drill) — part A: a pipelined serve child
   (``SPARSE_TPU_INFLIGHT=4``) SIGKILLs itself with bucket programs
   genuinely IN FLIGHT (``flush(wait=False)``, no drain); the fresh
   process constructs with the ASYNC warm replay and submits the
   backlog immediately — the dispatch path must wait for the replay's
   programs instead of rebuilding them, serving the backlog with ZERO
   serving-path builds (plan-cache misses caused by serving
   dispatches), all lanes converged. Part B: a burst submitted against
   ``max_queue_depth`` backpressure must emit ``batch.admission``
   events, drive the ``queue_depth`` watchdog rule to alert DURING the
   burst and clear after the drain — the admission/alerting loop
   proven end-to-end, on top of zero gauge drift.
11. **Preconditioner chaos** (ISSUE 14 acceptance drill) — part A: a
   ``nonfinite:precond`` fault clause poisons the preconditioner apply
   (the operator stays pristine) under ``solve_with_recovery(M=...)``:
   the ladder must classify the corruption as nonfinite-in-M
   (``nonfinite_m``), take the DROP-PRECONDITIONER rung (a
   ``solver.retry`` event with ``action='drop_precond'`` — no solver
   escalation spent), re-solve clean and converge, with the full
   ``fault.injected(site=precond) -> solver.retry -> solver.recovered``
   chain in the log. Part B: a ``bitflip:io`` clause against the
   ILU(0) symbolic vault artifact: the corrupted read must quarantine
   and rebuild (``vault.quarantine``), and the rebuilt symbolic
   structure must factorize to the EXACT factor the pre-corruption
   artifact produced — disk corruption can never change the numerics.
12. **Mixed-precision chaos** (ISSUE 15 acceptance drill) — part A: a
   bounded ``nonfinite:matvec`` clause corrupts the reduced-precision
   (``dtype_policy='f32ir'``) bucket's inner f32 sweep: the anomaly
   detectors fire (``solver.anomaly``), the session takes the
   PROMOTE_DTYPE rung — a ``mixed.promote`` event plus a
   ``batch.requeue`` with ``action='promote_dtype'``, the group pinned
   to 'exact' (``mixed.promotions`` counter) — and the ticket still
   converges through the exact re-solve AHEAD of any solver
   escalation. Part B: the reduced-precision program's manifest entry
   carries its ``dtype_policy``; after clean traffic, a fresh process
   replays the precision-KEYED (``.Pf32ir``-suffixed) program and
   serves the mixed fast path at ZERO plan-cache misses.
13. **Autopilot regression** (ISSUE 16 acceptance drill) — the online
   policy tuner converges on live traffic, then the drill PLANTS a
   regression: a bad reduced-precision decision forced over every
   group with an optimistic score (``force_decision``). Pinned
   observations must strike the ``autopilot.drift_strikes`` counter,
   the ``autopilot_drift`` watchdog rule must alert and re-open
   exploration through the process-global hook (``autopilot.reopen``
   with a ``watchdog:`` reason), the group must re-converge from
   fresh measurements (a second ``autopilot.converge``), every lane
   stays converged throughout, and the re-converged decision artifact
   must survive a vault restart — a FRESH tuner restores it
   (``autopilot.restore``) and serves tuned from the first request
   with zero trials.
14. **Ingest chaos** (ISSUE 18 acceptance drill) — part A: a seeded
   loadgen trace with a nonzero unseen-pattern ``ingest`` arrival rate
   drives a warm ``SolveSession`` while ``truncate:io`` faults tear the
   onboarder's vault writes: the solve p95 must hold within the SLO
   through background onboarding, every arrival still onboards
   (latency reported separately), a torn pattern artifact quarantines
   on read-back and a fresh session rebuilds it to the IDENTICAL
   fingerprint. Part B: an ingest child SIGKILLs itself
   mid-onboarding; a genuinely fresh process replays the vaulted
   fingerprint index, dedups the re-arrival of the onboarded
   structure, and serves its first solve at ZERO plan-cache misses —
   dedup proven restart-surviving, not just in-process.
15. **Error-budget burn** (ISSUE 19 acceptance drill) — injected
   dispatch delays page the compressed-window ``slo_fast_burn`` rule,
   the alert bundle embeds the history window, the doctor names the
   burn signature, and ``axon_report --history`` shows the incident
   window from a fresh process.
16. **Elastic mesh** (ISSUE 20 acceptance drill) — a child on the
   forced 8-device mesh serves seeded loadgen traffic across a LIVE
   topology shrink-and-regain (``remesh:at=...,to=4`` then ``to=8``):
   every solve ticket reaches a terminal state (zero lost across both
   migrations), queue gauges read zero after the drain, the vault
   manifest carries both mesh fingerprints, the post-recovery window
   serves at ZERO plan-cache misses (recovery is a warm replay, not a
   rebuild), and the stdlib doctor over the child's flight bundle
   names the ``mesh-topology-change`` signature.

Telemetry is pointed at a temp sink (never the committed
``results/axon/records.jsonl``). Wired into the quick lane through
``scripts/check_quick_lane.py``'s script-integrity list and exercised by
``tests/test_resilience.py``.

Usage:
    python scripts/chaos_check.py [--json]

(``--vault-child serve|warm`` is the internal entry point of scenario
6's subprocesses — it reads ``SPARSE_TPU_VAULT`` from the env; the
``-pipe``, ``ingest-`` and ``elastic`` modes are scenarios 10, 14 and
16's children.)
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")

#: the fixed chaos spec of scenarios 1/3 (seeded => bit-reproducible)
MATVEC_SPEC = "nonfinite:matvec:p=0.01,seed=7"
PREEMPT_SPEC = "preempt:chunk:p=0.25,seed=11,n=3"
N = 64
TOL = 1e-8
MAX_ATTEMPTS = 10


def _tridiag(n, seed=0):
    import numpy as np
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    e = np.ones(n)
    A = sp.diags([-e[:-1], 3.0 * e, -e[:-1]], [-1, 0, 1], format="csr")
    A = A.copy()
    A.setdiag(3.0 + rng.random(n))
    A.sort_indices()
    return A


def _event_kinds(tel):
    kinds: dict = {}
    for ev in tel.events():
        kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
    return kinds


def run(report: dict) -> list:
    """Run every scenario; returns a list of problem strings."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    import sparse_tpu
    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.checkpoint import checkpointed_cg
    from sparse_tpu.config import settings
    from sparse_tpu.resilience import (
        RecoveryPolicy,
        failover,
        faults,
        solve_with_recovery,
    )

    problems = []
    S = _tridiag(N)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(1).standard_normal(N)

    # -- 1. unbatched recovery under matvec corruption ----------------------
    for solver in ("cg", "bicgstab", "gmres"):
        tel.reset()
        faults.clear()
        faults.configure(MATVEC_SPEC)
        try:
            x, info = solve_with_recovery(
                A, b, solver=solver, tol=TOL,
                policy=RecoveryPolicy(max_attempts=MAX_ATTEMPTS),
            )
        finally:
            faults.clear()
        rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
        kinds = _event_kinds(tel)
        fires = sum(faults.stats().values()) or kinds.get("fault.injected", 0)
        report[f"solver.{solver}"] = {
            "converged": bool(info.converged), "attempts": info.attempts,
            "rnorm": rnorm, "events": kinds,
        }
        target = TOL * max(float(np.linalg.norm(b)), 1.0) \
            if solver == "gmres" else TOL
        if not info.converged or rnorm > 10 * target:
            problems.append(
                f"{solver}: failed to recover (converged={info.converged}, "
                f"||r||={rnorm:.2e})"
            )
        if kinds.get("fault.injected", 0) == 0:
            problems.append(f"{solver}: no fault.injected events — the "
                            "chaos spec injected nothing")
        if info.attempts > 1 and kinds.get("solver.retry", 0) == 0:
            problems.append(f"{solver}: recovery ran without solver.retry "
                            "events")
        if info.recovered and kinds.get("solver.recovered", 0) == 0:
            problems.append(f"{solver}: missing solver.recovered event")

    # -- 2. forced Pallas failure + probe reinstate -------------------------
    tel.reset()
    faults.configure("fail:pallas:kernel=sell_spmv,n=1")
    old_mode = settings.spmv_mode
    try:
        from sparse_tpu.kernels.sell_spmv import PreparedCSR

        settings.spmv_mode = "pallas"
        G = _tridiag(32).astype(np.float32)
        prep = PreparedCSR(G.indptr, G.indices, G.data, G.shape)
        xs = np.random.default_rng(2).standard_normal(32).astype(np.float32)
        y = np.asarray(prep(xs))
        ok = np.allclose(y, G @ xs, rtol=1e-5, atol=1e-5)
        kinds = _event_kinds(tel)
        latched = failover.failed(prep.KERNEL, prep)
        faults.clear()
        reinstated = prep.probe_pallas(xs.astype(np.float32))
        report["pallas_failover"] = {
            "result_ok": bool(ok), "latched": bool(latched),
            "reinstated": bool(reinstated), "events": _event_kinds(tel),
        }
        if not ok:
            problems.append("pallas failover: XLA fallback result wrong")
        if not latched or kinds.get("kernel.failover", 0) == 0:
            problems.append("pallas failover: no kernel.failover latch/event")
        if not reinstated or failover.failed(prep.KERNEL, prep):
            problems.append("pallas failover: probe did not reinstate")
    finally:
        settings.spmv_mode = old_mode
        faults.clear()

    # -- 3. batched recovery ------------------------------------------------
    tel.reset()
    faults.configure(MATVEC_SPEC)
    try:
        rng = np.random.default_rng(3)
        mats = []
        for _ in range(4):
            M = _tridiag(N)
            M.setdiag(3.0 + rng.random(N))
            mats.append(M.tocsr())
        rhs = rng.standard_normal((4, N))
        sess = SolveSession("cg")
        X, iters, resid2 = sess.solve_many(mats, rhs, tol=TOL)
    finally:
        faults.clear()
    lane_resids = [
        float(np.linalg.norm(m @ x - r)) for m, x, r in zip(mats, X, rhs)
    ]
    kinds = _event_kinds(tel)
    report["batch"] = {"lane_resids": lane_resids, "events": kinds}
    bad = [r for r in lane_resids if not (r <= 10 * TOL)]
    if bad:
        problems.append(f"batch: {len(bad)} lanes failed to recover "
                        f"(worst ||r||={max(bad):.2e})")
    if kinds.get("batch.dispatch", 0) == 0:
        problems.append("batch: no batch.dispatch events")

    # -- 4. preemption + checkpoint resume ----------------------------------
    tel.reset()
    faults.configure(PREEMPT_SPEC)
    ck = os.path.join(tempfile.mkdtemp(prefix="chaos_ck_"), "cg.npz")
    x = None
    resumes = 0
    try:
        for _ in range(8):  # preempt budget n=3 bounds this
            try:
                x, _ = checkpointed_cg(A, b, ck, tol=TOL, chunk=20)
                break
            except faults.Preempted:
                resumes += 1
        else:
            problems.append("preempt: solve never completed")
    finally:
        faults.clear()
    if x is not None:
        rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
        report["preempt"] = {"resumes": resumes, "rnorm": rnorm}
        if rnorm > 10 * TOL:
            problems.append(f"preempt: resumed solve wrong (||r||={rnorm:.2e})")
        if resumes == 0:
            problems.append("preempt: injection never fired (spec drift?)")

    # -- 5. vault io chaos: corruption quarantines, never escapes -----------
    problems += _vault_io_chaos(report)

    # -- 6. kill-and-restart: warm replay serves at zero misses -------------
    problems += _vault_kill_restart(report)

    # -- 7. kill-and-restart under FLEET mode: mesh-keyed manifest ----------
    problems += _fleet_kill_restart(report)

    # -- 8. loadgen traffic + watchdog alert/clear under dispatch delay -----
    problems += _loadgen_watchdog(report)

    # -- 9. incident flight recorder: alert -> bundle -> doctor diagnosis ---
    problems += _incident_flight(report)

    # -- 10. pipeline restart (kill with buckets in flight) + admission -----
    problems += _pipeline_restart_admission(report)

    # -- 11. precond chaos: drop-M rung + ILU artifact io parity ------------
    problems += _precond_chaos(report)

    # -- 12. mixed-precision chaos: promote_dtype rung + precision-keyed
    #        warm restart ---------------------------------------------------
    problems += _mixed_chaos(report)

    # -- 13. autopilot regression: drift -> watchdog reopen -> re-converge --
    problems += _autopilot_chaos(report)

    # -- 14. ingest chaos: io faults + kill mid-onboarding ------------------
    problems += _ingest_chaos(report)
    problems += _ingest_kill_restart(report)

    # -- 15. error-budget burn: fast-burn alert -> history-carrying bundle --
    problems += _budget_burn(report)

    # -- 16. elastic mesh: loadgen traffic across a live 8->4->8 remesh -----
    problems += _elastic_remesh(report)
    return problems


def _elastic_remesh(report: dict) -> list:
    """Scenario 16 (ISSUE 20 acceptance drill): a child on the forced
    8-device mesh serves seeded loadgen traffic ACROSS a live topology
    shrink-and-regain (``remesh:at=...,to=4`` then ``to=8`` trace
    clauses): every solve ticket must reach a terminal state (zero
    lost), the queue gauges must read zero after the drain, the vault
    manifest must carry BOTH mesh fingerprints (each transition was a
    warm replay), the post-recovery serving window must run at zero
    plan-cache misses, and the stdlib doctor over a flight bundle from
    the child must name the mesh-topology-change signature."""
    problems = []
    vdir = tempfile.mkdtemp(prefix="chaos_vault_elastic_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    env["SPARSE_TPU_VAULT"] = vdir
    env["SPARSE_TPU_COMPILE_CACHE"] = os.path.join(vdir, "_xla_cache")
    env["SPARSE_TPU_FLEET"] = "auto"
    env["SPARSE_TPU_FLEET_MIN_B"] = "2"
    env.pop("SPARSE_TPU_FAULTS", None)

    child = subprocess.run(
        [sys.executable, os.path.abspath(__file__),
         "--vault-child", "elastic"],
        env=env, capture_output=True, text=True, timeout=300,
    )
    out = None
    for line in child.stdout.splitlines():
        if line.startswith("ELASTIC "):
            try:
                out = json.loads(line[8:])
            except json.JSONDecodeError:
                pass
    if out is None:
        problems.append(
            f"elastic: child produced no report (rc={child.returncode}, "
            f"stderr tail: {child.stderr[-300:]!r})"
        )
        return problems
    report["elastic_remesh"] = out
    rep = out.get("report", {})
    if rep.get("failed", 1) != 0:
        problems.append(
            f"elastic: {rep.get('failed')} ticket(s) failed across the "
            "remesh (zero-loss migration broken)"
        )
    if rep.get("completed", 0) != rep.get("arrivals", -1):
        problems.append(
            f"elastic: {rep.get('completed')}/{rep.get('arrivals')} "
            "tickets terminal after drain"
        )
    if rep.get("remeshes", {}).get("ok", 0) < 1:
        problems.append(
            f"elastic: the traced shrink never executed, got "
            f"{rep.get('remeshes')}"
        )
    if out.get("recover", {}).get("outcome") != "ok":
        problems.append(
            f"elastic: the recovery remesh did not execute "
            f"(got {out.get('recover')})"
        )
    if out.get("drift", 1) != 0:
        problems.append(
            f"elastic: queue gauges drifted by {out.get('drift')} after "
            "the drain (a ticket was dropped or double-counted)"
        )
    fp = str(out.get("mesh", {}).get("fingerprint", ""))
    if fp.split(":")[1:2] != ["8"]:
        problems.append(
            f"elastic: live mesh identity {fp!r} did not recover to the "
            "8-device mesh (stale identity?)"
        )
    meshes = {m for m in out.get("manifest_mesh", []) if m}
    if len(meshes) < 2:
        problems.append(
            f"elastic: vault manifest carries {sorted(meshes)} — both "
            "topologies' programs should have been vaulted"
        )
    d = out.get("delta", {})
    if d.get("misses", 1) != 0:
        problems.append(
            f"elastic: post-recovery window had {d.get('misses')} "
            "plan-cache misses (recovery must be a warm replay)"
        )
    if d.get("hits", 0) < 1:
        problems.append("elastic: post-recovery window saw no cache hits")
    bad = [r for r in out.get("resids", [1.0]) if not (r <= 10 * TOL)]
    if bad:
        problems.append(
            f"elastic: {len(bad)} lanes unconverged after recovery "
            f"(worst ||r||={max(bad):.2e})"
        )
    bundle = out.get("bundle")
    if not bundle or not os.path.isdir(bundle):
        problems.append("elastic: child captured no flight bundle")
        return problems
    doctor = subprocess.run(
        [sys.executable, os.path.join(HERE, "axon_doctor.py"), bundle,
         "--json"],
        capture_output=True, text=True, timeout=60,
    )
    try:
        diag = json.loads(doctor.stdout)
    except json.JSONDecodeError:
        diag = None
    if diag is None:
        problems.append(
            f"elastic: doctor produced no JSON diagnosis "
            f"(rc={doctor.returncode}, stderr: {doctor.stderr[-200:]!r})"
        )
        return problems
    report["elastic_remesh"]["diagnosis"] = {
        "cause": diag.get("cause"),
        "probable_cause": diag.get("probable_cause"),
    }
    if diag.get("cause") != "mesh-topology-change":
        problems.append(
            f"elastic: doctor named {diag.get('cause')!r}, not "
            "'mesh-topology-change'"
        )
    return problems


def _budget_burn(report: dict) -> list:
    """Scenario 15 (ISSUE 19): scenario 8's injection geometry against
    the v7 error-budget watchdog with the history sampler live. Under
    injected dispatch delays the ``slo_fast_burn`` rule (windows
    compressed from 5m/1h to fractions of a second) must page while the
    faults are live and clear once clean traffic has rolled the short
    window over; the alert's flight bundle must embed the history
    window, the stdlib doctor must name the burn signature, and
    ``axon_report --history`` over the sampler's segments must show the
    incident window from a fresh process."""
    import numpy as np

    from sparse_tpu import loadgen, telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.resilience import faults
    from sparse_tpu.telemetry import _budget, _flight, _history, _watchdog

    problems = []
    tel.reset()
    rng = np.random.default_rng(53)
    mats = []
    for _ in range(4):
        M = _tridiag(N)
        M.setdiag(3.0 + rng.random(N))
        M.sort_indices()
        mats.append(M.tocsr())
    rhs = rng.standard_normal((4, N))
    systems = list(zip(mats, rhs))

    ses = SolveSession("cg", slo_ms=WD_SLO_MS)
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()
    bkt = 1
    while bkt <= 16:
        ses._prebuild(pattern, "cg", bkt, np.dtype(np.float64))
        bkt *= 2

    hdir = tempfile.mkdtemp(prefix="chaos_history_")
    idir = tempfile.mkdtemp(prefix="chaos_incidents_")
    _history.stop()
    _history.start(root=hdir, interval_s=0.05)
    _flight.stop_flight()
    _flight.flight(root=idir, min_interval_s=60.0, max_bundles=4)
    # a fresh engine, its 5m/1h geometry compressed to fractions of a
    # second so the drill's faulted/clean phases ARE the windows
    eng = _budget.Engine()
    wd = _watchdog.Watchdog(rules=[
        _budget.fast_burn_rule(windows=(0.5, 2.0), engine=eng),
    ])
    wd.evaluate()  # prime: first engine sample, rule skips (no pair yet)

    trace = loadgen.ArrivalTrace.poisson(rate=40.0, duration=0.5, seed=19)
    faults.configure(WD_DELAY_SPEC)
    try:
        loadgen.run_load(ses, trace, systems, tol=TOL)
        # evaluate while the injection is live: every ticket of the
        # faulted run missed, so both compressed windows burn far past
        # the 14.4 trigger and the page fires DURING the incident
        wd.evaluate()
        alerted = "slo_fast_burn" in wd.active()
    finally:
        faults.clear()
    # clean traffic rolls the short window past the incident: the
    # post-fault delta (sampled at each evaluation) is miss-free, the
    # min-across-pair drops under clear
    cleared = False
    for _ in range(3):
        loadgen.run_load(ses, trace, systems, tol=TOL)
        wd.evaluate()
        if "slo_fast_burn" not in wd.active():
            cleared = True
            break
    _history.stop()
    _flight.stop_flight()

    kinds = _event_kinds(tel)
    bundles = sorted(
        n for n in os.listdir(idir)
        if os.path.isfile(os.path.join(idir, n, "incident.json"))
    )
    segs = _history.read_segments(hdir)
    report["budget_burn"] = {
        "alerted_during_injection": alerted,
        "cleared_after_clean": cleared,
        "bundles": bundles,
        "history_points": len(segs),
        "events": kinds,
    }
    if not alerted:
        problems.append("budget: slo_fast_burn did not page during "
                        "injection")
    if not cleared:
        problems.append(
            f"budget: fast burn did not clear after clean traffic "
            f"(active={wd.active()})"
        )
    if kinds.get("budget.burn", 0) == 0:
        problems.append("budget: no budget.burn breadcrumb event")
    if not segs:
        problems.append("budget: history sampler committed no segments")
    if len(bundles) != 1:
        problems.append(
            f"budget: expected one alert bundle, found {len(bundles)}"
        )
        return problems
    bundle = os.path.join(idir, bundles[0])
    try:
        hist = json.load(open(os.path.join(bundle, "history.json")))
        hpoints = len(hist.get("points", []))
    except (OSError, json.JSONDecodeError, ValueError):
        hpoints = None
    report["budget_burn"]["bundle_history_points"] = hpoints
    if not hpoints:
        problems.append("budget: bundle carries no history.json window")
    # the stdlib doctor: the injected delay stays the probable cause,
    # and the burn signature must be named among the matches
    doctor = subprocess.run(
        [sys.executable, os.path.join(HERE, "axon_doctor.py"), bundle,
         "--json"],
        capture_output=True, text=True, timeout=60,
    )
    try:
        diag = json.loads(doctor.stdout)
    except json.JSONDecodeError:
        diag = None
    if diag is None:
        problems.append(
            f"budget: doctor produced no JSON diagnosis "
            f"(rc={doctor.returncode}, stderr: {doctor.stderr[-200:]!r})"
        )
        return problems
    match_ids = [m.get("id") for m in diag.get("matches", [])]
    report["budget_burn"]["diagnosis"] = {
        "rule": diag.get("rule"), "cause": diag.get("cause"),
        "matches": match_ids,
    }
    if diag.get("rule") != "slo_fast_burn":
        problems.append(
            f"budget: diagnosis rule {diag.get('rule')!r} != "
            "'slo_fast_burn'"
        )
    if "slo-error-budget-burn" not in match_ids:
        problems.append("budget: doctor did not name the burn signature")
    # a FRESH process joins the committed segments and reports the
    # incident window (the cross-restart read path)
    rep_hist = subprocess.run(
        [sys.executable, os.path.join(HERE, "axon_report.py"),
         "--history", hdir],
        capture_output=True, text=True, timeout=60,
    )
    report["budget_burn"]["report_history_rc"] = rep_hist.returncode
    if rep_hist.returncode != 0:
        problems.append(
            f"budget: axon_report --history failed "
            f"(rc={rep_hist.returncode}, stderr: "
            f"{rep_hist.stderr[-200:]!r})"
        )
    elif "incident window" not in rep_hist.stdout:
        problems.append("budget: axon_report --history did not show the "
                        "incident window")
    return problems


def _autopilot_chaos(report: dict) -> list:
    """Scenario 13 (ISSUE 16): a planted policy regression mid-run. The
    tuner converges on live traffic, the drill then forces a bad
    (reduced-precision) decision with an optimistic planted score — the
    'environment changed under the pinned policy' shape. Pinned
    observations must strike the ``autopilot.drift_strikes`` counter,
    the :func:`drift_rule` watchdog alert must re-open exploration
    through the process-global hook (``autopilot.reopen`` with a
    ``watchdog:`` reason), the group must CONVERGE BACK from fresh
    measurements, and the re-converged decision artifact must survive a
    vault restart (a fresh tuner serves it from the first request)."""
    import shutil

    import numpy as np

    from sparse_tpu import autopilot, plan_cache
    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings
    from sparse_tpu.resilience import faults
    from sparse_tpu.telemetry import _metrics, _watchdog

    problems = []
    tel.reset()
    faults.clear()
    vdir = tempfile.mkdtemp(prefix="chaos_autopilot_vault_")
    old_vault = settings.vault
    settings.vault = vdir
    try:
        plan_cache.clear()
        rng = np.random.default_rng(61)
        mats = []
        for _ in range(4):
            M = _tridiag(N)
            M.setdiag(3.0 + rng.random(N))
            M.sort_indices()
            mats.append(M.tocsr())
        rhs = rng.standard_normal((4, N))

        ap = autopilot.Autopilot(grid=({}, {"precond": "jacobi"}),
                                 epsilon=1.0, trials=1, drift=2.0)
        ses = SolveSession("cg", warm_start=False, autopilot=ap)

        def group():
            groups = list(ses.session_stats().get(
                "autopilot", {}).get("groups", {}).values())
            return groups[0] if groups else {}

        def serve(times=1):
            worst = 0.0
            for _ in range(times):
                X, _i, _r2 = ses.solve_many(mats, rhs, tol=TOL)
                worst = max(worst, max(
                    float(np.linalg.norm(m @ x - b))
                    for m, x, b in zip(mats, X, rhs)))
            return worst

        # phase 1: converge on live traffic
        for flushes in range(1, 31):
            worst = serve()
            if group().get("phase") == "converged":
                break
        g1 = group()
        if g1.get("phase") != "converged":
            problems.append("autopilot: tuner never converged on clean "
                            f"traffic ({flushes} flushes)")
            return problems
        arm1, score1 = g1["arm"], g1["score_ms"]

        # the drift watchdog primes BEFORE the regression (windowed
        # delta: first tick snapshots, later ticks see new strikes)
        wd = _watchdog.Watchdog(rules=[autopilot.drift_rule()],
                                interval_s=0.0)
        wd.evaluate()
        quiet = wd.evaluate()
        if any(t.get("rule") == "autopilot_drift" for t in quiet):
            problems.append("autopilot: drift rule fired before the "
                            "planted regression")

        # phase 2: plant the regression — a reduced-precision arm pinned
        # with a score real traffic cannot meet (belief vs reality)
        strikes0 = float(_metrics.counter("autopilot.drift_strikes").value)
        ap.force_decision({"dtype_policy": "f32ir"},
                          score=max(score1, 1e-3) / 4.0)
        worst = max(worst, serve(times=3))
        strikes = float(
            _metrics.counter("autopilot.drift_strikes").value) - strikes0
        transitions = wd.evaluate()
        alerted = any(
            t.get("event") == "alert" and t.get("rule") == "autopilot_drift"
            for t in transitions)
        g2 = group()

        # phase 3: converge back from fresh measurements
        for reflushes in range(1, 31):
            worst = max(worst, serve())
            if group().get("phase") == "converged":
                break
        g3 = group()

        kinds = _event_kinds(tel)
        reopen_reasons = [
            e.get("reason") for e in tel.events()
            if e.get("kind") == "autopilot.reopen"
        ]
        report["autopilot_chaos"] = {
            "converged_arm": arm1, "score_ms": score1,
            "drift_strikes": strikes, "alerted": alerted,
            "reopened_phase": g2.get("phase"),
            "reopen_reasons": reopen_reasons,
            "reconverged": g3, "worst_resid": worst, "events": kinds,
        }
        if strikes < 1:
            problems.append("autopilot: planted regression produced no "
                            "drift strikes")
        if not alerted:
            problems.append("autopilot: drift watchdog rule never alerted")
        if g2.get("phase") != "exploring":
            problems.append("autopilot: watchdog alert did not re-open "
                            f"exploration (phase={g2.get('phase')!r})")
        if kinds.get("autopilot.reopen", 0) < 1 or not any(
                str(r).startswith("watchdog:") for r in reopen_reasons):
            problems.append("autopilot: no autopilot.reopen event with a "
                            "watchdog: reason")
        if kinds.get("autopilot.converge", 0) < 2:
            problems.append("autopilot: no second autopilot.converge "
                            "after the reopen")
        if g3.get("phase") != "converged":
            problems.append("autopilot: tuner never re-converged after "
                            f"the regression ({reflushes} flushes)")
        if worst > 10 * TOL:
            problems.append(f"autopilot: a lane went unconverged during "
                            f"the drill (worst ||r||={worst:.2e})")

        # phase 4: the re-converged decision survives a vault restart —
        # fresh process (tier 1 cleared, NEW tuner), tuned immediately
        plan_cache.clear()
        ap2 = autopilot.Autopilot(grid=({}, {"precond": "jacobi"}),
                                  epsilon=1.0, trials=1, drift=2.0)
        ses = SolveSession("cg", warm_start=True, warm_async=False,
                           autopilot=ap2)
        worst2 = serve()
        gr = group()
        restored_events = _event_kinds(tel).get("autopilot.restore", 0)
        report["autopilot_chaos"]["restart"] = {
            "restored": gr.get("restored"), "arm": gr.get("arm"),
            "trials": gr.get("trials"), "replayed": ses.warm_replayed,
            "restore_events": restored_events, "worst_resid": worst2,
        }
        if not gr.get("restored") or gr.get("phase") != "converged":
            problems.append("autopilot: decision artifact did not survive "
                            "the vault restart")
        if gr.get("arm") != g3.get("arm"):
            problems.append(
                f"autopilot: restart restored arm {gr.get('arm')!r}, "
                f"expected the re-converged {g3.get('arm')!r}")
        if gr.get("trials", 1) != 0:
            problems.append("autopilot: restored group spent trials "
                            "re-exploring (expected tuned-from-first-"
                            "request)")
        if restored_events < 1:
            problems.append("autopilot: no autopilot.restore event on the "
                            "restart")
        if worst2 > 10 * TOL:
            problems.append("autopilot: restart traffic unconverged "
                            f"(worst ||r||={worst2:.2e})")
    finally:
        settings.vault = old_vault
        plan_cache.clear()
    shutil.rmtree(vdir, ignore_errors=True)
    return problems


def _mixed_chaos(report: dict) -> list:
    """Scenario 12 (ISSUE 15): matvec corruption scoped into the inner
    f32 sweep of a reduced-precision bucket must take the promote_dtype
    rung — anomaly detected, lanes requeued at 'exact', ticket still
    converged — and a warm restart must replay the precision-keyed
    program at zero serving misses."""
    import shutil

    import numpy as np

    from sparse_tpu import plan_cache, vault
    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings
    from sparse_tpu.resilience import faults
    from sparse_tpu.telemetry import _metrics

    problems = []
    S = _tridiag(N, seed=31)
    import sparse_tpu

    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(33).standard_normal(N)

    # -- part A: inner-sweep corruption => promote_dtype rung ---------------
    tel.reset()
    faults.clear()
    plan_cache.clear()
    # bounded clause: the injection budget exhausts during the reduced
    # bucket's inner sweep, so the promoted exact re-solve runs clean
    faults.configure("nonfinite:matvec:p=1,n=6,seed=13")

    def _promos():
        # the IR loop's divergence safeguard may classify the corrupted
        # lane as unconverged (finite best iterate) rather than
        # nonfinite — both are the injected anomaly
        return sum(
            float(_metrics.counter("mixed.promotions", reason=r).value)
            for r in ("nonfinite", "unconverged")
        )

    promo0 = _promos()
    try:
        ses = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
        t = ses.submit(A, b, tol=TOL, maxiter=20 * N)
        ses.flush()
        x, _iters, r2 = t.result()
    finally:
        faults.clear()
    rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
    kinds = _event_kinds(tel)
    promos = _promos() - promo0
    requeue_actions = [
        e.get("action") for e in tel.events()
        if e.get("kind") == "batch.requeue"
    ]
    report["mixed_promote"] = {
        "converged": bool(t.converged), "rnorm": rnorm,
        "promoted": bool(t.promoted), "promotions": promos,
        "requeue_actions": requeue_actions, "events": kinds,
    }
    if not t.converged or rnorm > 10 * TOL:
        problems.append(
            f"mixed: promoted solve failed (converged={t.converged}, "
            f"||r||={rnorm:.2e})"
        )
    if kinds.get("fault.injected", 0) == 0:
        problems.append("mixed: no fault.injected events — spec drift?")
    if kinds.get("solver.anomaly", 0) == 0:
        problems.append("mixed: anomaly detector never fired on the "
                        "corrupted inner sweep")
    if kinds.get("mixed.promote", 0) == 0 or promos < 1:
        problems.append("mixed: promote_dtype rung never fired")
    if "promote_dtype" not in requeue_actions:
        problems.append("mixed: no batch.requeue with "
                        "action='promote_dtype'")
    if not t.promoted:
        problems.append("mixed: ticket not marked promoted")

    # -- part B: precision-keyed warm restart at zero serving misses --------
    vdir = tempfile.mkdtemp(prefix="chaos_mixed_vault_")
    old_vault = settings.vault
    try:
        settings.vault = vdir
        plan_cache.clear()
        ses1 = SolveSession("cg", warm_start=False, dtype_policy="f32ir")
        t1 = ses1.submit(A, b, tol=TOL, maxiter=20 * N)
        ses1.flush()
        t1.result()
        entries = vault.manifest_entries()
        keyed = [e for e in entries if e.get("dtype_policy") == "f32ir"]
        # the restart: in-process tier cleared, vault retained
        plan_cache.clear()
        ses2 = SolveSession("cg", warm_start=True, warm_async=False,
                            dtype_policy="f32ir")
        replayed = ses2.warm_replayed
        snap = plan_cache.snapshot()
        t2 = ses2.submit(A, b, tol=TOL, maxiter=20 * N)
        ses2.flush()
        x2, _i2, _r2 = t2.result()
        d = plan_cache.delta(snap)
        rnorm2 = float(np.linalg.norm(S @ np.asarray(x2) - b))
        report["mixed_warm_restart"] = {
            "manifest_keyed": len(keyed), "replayed": replayed,
            "serving_misses": d["misses"], "rnorm": rnorm2,
        }
        if not keyed:
            problems.append("mixed: manifest entry lost its dtype_policy")
        if replayed < 1:
            problems.append("mixed: warm replay rebuilt no precision-"
                            "keyed program")
        if d["misses"] != 0:
            problems.append(
                f"mixed: warm restart served with {d['misses']} plan-"
                "cache misses (expected zero)"
            )
        if not t2.converged or rnorm2 > 10 * TOL:
            problems.append("mixed: warm-restart solve failed")
    finally:
        settings.vault = old_vault
        shutil.rmtree(vdir, ignore_errors=True)
    return problems


def _precond_chaos(report: dict) -> list:
    """Scenario 11 (ISSUE 14): corruption scoped INSIDE the
    preconditioner apply must take the ladder's drop-preconditioner
    rung (distinctly classified, no solver escalation), and io
    corruption of the ILU(0) symbolic vault artifact must quarantine +
    rebuild to bit-identical factors."""
    import numpy as np

    import sparse_tpu
    from sparse_tpu import plan_cache, precond, vault
    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch.operator import SparsityPattern
    from sparse_tpu.config import settings
    from sparse_tpu.precond import ilu as pilu
    from sparse_tpu.resilience import RecoveryPolicy, faults, \
        solve_with_recovery

    problems = []
    S = _tridiag(N, seed=21)
    A = sparse_tpu.csr_array(S)
    b = np.random.default_rng(23).standard_normal(N)

    # -- part A: nonfinite scoped inside the M apply => drop rung -----------
    tel.reset()
    faults.clear()
    # unbounded on purpose: the clause only targets the precond site,
    # so the drop rung REMOVES the corruption source — the clean
    # re-solve sees no fires, and the classifier probe (which must
    # observe M misbehaving) always has budget left
    faults.configure("nonfinite:precond:p=1")
    try:
        M = precond.make_M(A, "jacobi")
        x, info = solve_with_recovery(
            A, b, solver="cg", tol=TOL, M=M,
            policy=RecoveryPolicy(max_attempts=MAX_ATTEMPTS),
        )
    finally:
        faults.clear()
    rnorm = float(np.linalg.norm(S @ np.asarray(x) - b))
    kinds = _event_kinds(tel)
    retries = [
        e for e in tel.events() if e.get("kind") == "solver.retry"
    ]
    dropped = [
        e for e in retries if e.get("action") == "drop_precond"
    ]
    report["precond_drop"] = {
        "converged": bool(info.converged), "attempts": info.attempts,
        "rnorm": rnorm, "events": kinds,
        "retry_actions": [
            (e.get("action"), e.get("reason")) for e in retries
        ],
    }
    if not info.converged or rnorm > 10 * TOL:
        problems.append(
            f"precond drop: failed to recover (converged="
            f"{info.converged}, ||r||={rnorm:.2e})"
        )
    if not any(
        e.get("kind") == "fault.injected" and e.get("site") == "precond"
        for e in tel.events()
    ):
        problems.append("precond drop: no fault.injected at site=precond")
    if not dropped:
        problems.append(
            "precond drop: ladder never took the drop_precond rung"
        )
    elif dropped[0].get("reason") != "nonfinite_m":
        problems.append(
            "precond drop: corruption in M not classified nonfinite_m "
            f"(got {dropped[0].get('reason')!r})"
        )
    if info.recovered and kinds.get("solver.recovered", 0) == 0:
        problems.append("precond drop: missing solver.recovered event")

    # -- part B: bitflipped ILU(0) symbolic artifact => quarantine + parity -
    tel.reset()
    vdir = tempfile.mkdtemp(prefix="chaos_precond_vault_")
    old_vault = settings.vault
    settings.vault = vdir
    try:
        plan_cache.clear()
        vault.reset_stats()
        pat = SparsityPattern(S.indptr, S.indices, S.shape)
        sym = pilu.ilu0_symbolic(pat, "ilu0")  # builds + deposits
        vals = np.asarray(S.data)[None, :]
        F_ref = np.asarray(pilu.factorize(sym, vals, sweeps=30))
        # a fresh pattern OBJECT (same content) misses the in-process
        # tier; the disk read comes back bitflipped and must quarantine
        plan_cache.clear()
        faults.configure("bitflip:io:p=1,n=1,seed=5")
        try:
            pat2 = SparsityPattern(S.indptr, S.indices, S.shape)
            sym2 = pilu.ilu0_symbolic(pat2, "ilu0")
        finally:
            faults.clear()
        F_re = np.asarray(pilu.factorize(sym2, vals, sweeps=30))
        vstats = vault.stats()
        qdir = vault.quarantine_dir()
        qfiles = os.listdir(qdir) if os.path.isdir(qdir) else []
        report["precond_vault_io"] = {
            "quarantined": int(vstats.get("quarantined", 0)),
            "quarantine_files": len(qfiles),
            "factor_max_err": float(np.abs(F_re - F_ref).max()),
        }
        if not qfiles and not vstats.get("quarantined", 0):
            problems.append(
                "precond vault io: corrupted ilu_symbolic read was not "
                "quarantined"
            )
        if not np.array_equal(F_re, F_ref):
            problems.append(
                "precond vault io: rebuilt symbolic factorizes "
                "differently (max err "
                f"{float(np.abs(F_re - F_ref).max()):.2e})"
            )
    finally:
        settings.vault = old_vault
        faults.clear()
        plan_cache.clear()
    return problems


#: scenario 8's injection/objective geometry: the injected delay must
#: dominate the SLO, and clean warm solves must sit far under it
WD_SLO_MS = 100.0
WD_DELAY_SPEC = "delay:dispatch:ms=150"


def _loadgen_watchdog(report: dict) -> list:
    """Scenario 8: drive loadgen traffic through a warm SolveSession
    with dispatch-delay injection; the watchdog's ``slo_miss_rate`` rule
    must alert while the faults are live and clear once clean traffic
    flows again."""
    import numpy as np

    from sparse_tpu import loadgen, telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.resilience import faults
    from sparse_tpu.telemetry import _watchdog

    problems = []
    tel.reset()
    rng = np.random.default_rng(31)
    mats = []
    for _ in range(4):
        M = _tridiag(N)
        M.setdiag(3.0 + rng.random(N))
        M.sort_indices()
        mats.append(M.tocsr())
    rhs = rng.standard_normal((4, N))
    systems = list(zip(mats, rhs))

    ses = SolveSession("cg", slo_ms=WD_SLO_MS)
    # warm every pow2 bucket the trace's coalescing can produce, so the
    # clean phase's latency is solve time, not compile tax
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()
    bkt = 1
    while bkt <= 16:
        ses._prebuild(pattern, "cg", bkt, np.dtype(np.float64))
        bkt *= 2

    wd = _watchdog.Watchdog(rules=[
        _watchdog.slo_miss_rate_rule(trigger=0.5, clear=0.2),
    ])
    wd.evaluate()  # prime the windowed-rate snapshots

    trace = loadgen.ArrivalTrace.poisson(rate=40.0, duration=0.5, seed=13)
    faults.configure(WD_DELAY_SPEC)
    try:
        rep_faulted = loadgen.run_load(ses, trace, systems, tol=TOL)
        # evaluate while the injection is still configured: the alert
        # must fire DURING the incident, not in the postmortem
        wd.evaluate()
        alerted = "slo_miss_rate" in wd.active()
    finally:
        faults.clear()
    rep_clean = loadgen.run_load(ses, trace, systems, tol=TOL)
    wd.evaluate()
    kinds = _event_kinds(tel)
    report["loadgen_watchdog"] = {
        "faulted": {
            "slo_miss_rate": rep_faulted.slo_miss_rate,
            "p95_ms": rep_faulted.latency_ms["p95"],
            "achieved_rps": rep_faulted.achieved_rps,
        },
        "clean": {
            "slo_miss_rate": rep_clean.slo_miss_rate,
            "p95_ms": rep_clean.latency_ms["p95"],
            "achieved_rps": rep_clean.achieved_rps,
        },
        "alerted_during_injection": alerted,
        "active_after_clean": wd.active(),
        "events": kinds,
    }
    if rep_faulted.completed == 0:
        problems.append("loadgen: faulted run completed no requests")
    if rep_faulted.slo_miss_rate <= 0.5:
        problems.append(
            f"loadgen: injected delay missed too few SLOs "
            f"(rate={rep_faulted.slo_miss_rate}) — spec drift?"
        )
    if kinds.get("fault.injected", 0) == 0:
        problems.append("loadgen: no fault.injected events from the "
                        "delay clause")
    if kinds.get("loadgen.trace", 0) < 2:
        problems.append("loadgen: missing loadgen.trace run records")
    if not alerted or kinds.get("watchdog.alert", 0) == 0:
        problems.append(
            "watchdog: slo_miss_rate did not alert during injection"
        )
    if wd.active() or kinds.get("watchdog.clear", 0) == 0:
        problems.append(
            f"watchdog: alert did not clear after faults lifted "
            f"(active={wd.active()}, clean slo_miss_rate="
            f"{rep_clean.slo_miss_rate})"
        )
    return problems


def _incident_flight(report: dict) -> list:
    """Scenario 9 (ISSUE 12): scenario 8's injection geometry with the
    flight recorder armed and sampled device profiling on. The watchdog
    alert during the incident must auto-capture exactly one rate-limited
    bundle whose ring tail carries the fault chain; the stdlib doctor
    must then name the injected delay as the probable cause."""
    import numpy as np

    from sparse_tpu import loadgen, telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.resilience import faults
    from sparse_tpu.telemetry import _flight, _watchdog

    problems = []
    tel.reset()
    rng = np.random.default_rng(41)
    mats = []
    for _ in range(4):
        M = _tridiag(N)
        M.setdiag(3.0 + rng.random(N))
        M.sort_indices()
        mats.append(M.tocsr())
    rhs = rng.standard_normal((4, N))
    systems = list(zip(mats, rhs))

    # sampled timed dispatches (profile_every=2): the bundle's ring tail
    # must show MEASURED device_ms on dispatch events, not just wall
    ses = SolveSession("cg", slo_ms=WD_SLO_MS, profile_every=2)
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()
    bkt = 1
    while bkt <= 16:
        ses._prebuild(pattern, "cg", bkt, np.dtype(np.float64))
        bkt *= 2

    idir = tempfile.mkdtemp(prefix="chaos_incidents_")
    _flight.stop_flight()
    fr = _flight.flight(root=idir, min_interval_s=60.0, max_bundles=4)
    wd = _watchdog.Watchdog(rules=[
        _watchdog.slo_miss_rate_rule(trigger=0.5, clear=0.2),
    ])
    wd.evaluate()  # prime the windowed-rate snapshots

    trace = loadgen.ArrivalTrace.poisson(rate=40.0, duration=0.5, seed=17)
    faults.configure(WD_DELAY_SPEC)
    try:
        loadgen.run_load(ses, trace, systems, tol=TOL)
        # the alert transition IS the capture trigger: evaluating while
        # the injection is live must write the bundle
        wd.evaluate()
        alerted = "slo_miss_rate" in wd.active()
        # a second alert inside the rate-limit window must be suppressed
        # (ONE bundle per incident window, never a disk flood)
        fr.on_alert({"rule": "slo_miss_rate", "severity": "page",
                     "value": 1.0, "trigger": 0.5})
    finally:
        faults.clear()
        _flight.stop_flight()

    bundles = sorted(
        n for n in os.listdir(idir)
        if os.path.isfile(os.path.join(idir, n, "incident.json"))
    )
    report["incident_flight"] = {
        "alerted": alerted,
        "bundles": bundles,
        "captures": fr.captures,
        "suppressed": fr.suppressed,
    }
    if not alerted:
        problems.append("flight: slo_miss_rate did not alert during "
                        "injection")
    if len(bundles) != 1:
        problems.append(
            f"flight: expected exactly one rate-limited bundle, found "
            f"{len(bundles)} ({bundles})"
        )
        return problems
    if fr.suppressed < 1:
        problems.append("flight: second alert was not counted as "
                        "suppressed")
    bundle = os.path.join(idir, bundles[0])
    ring = [
        json.loads(ln)
        for ln in open(os.path.join(bundle, "ring.jsonl"))
        if ln.strip()
    ]
    kinds = {}
    for ev in ring:
        kinds[ev.get("kind")] = kinds.get(ev.get("kind"), 0) + 1
    if kinds.get("fault.injected", 0) == 0:
        problems.append("flight: bundle ring tail carries no "
                        "fault.injected chain")
    sampled = [
        ev for ev in ring
        if ev.get("kind") == "batch.dispatch" and "device_ms" in ev
    ]
    if not sampled:
        problems.append("flight: no sampled batch.dispatch event with a "
                        "measured device_ms split in the bundle")
    # the stdlib doctor over the bundle: the probable cause must be the
    # injected dispatch delay, by id and by name
    doctor = subprocess.run(
        [sys.executable, os.path.join(HERE, "axon_doctor.py"), bundle,
         "--json"],
        capture_output=True, text=True, timeout=60,
    )
    diag = None
    try:
        diag = json.loads(doctor.stdout)
    except json.JSONDecodeError:
        pass
    if diag is None:
        problems.append(
            f"flight: doctor produced no JSON diagnosis "
            f"(rc={doctor.returncode}, stderr: {doctor.stderr[-200:]!r})"
        )
        return problems
    report["incident_flight"]["diagnosis"] = {
        "cause": diag.get("cause"),
        "probable_cause": diag.get("probable_cause"),
        "rule": diag.get("rule"),
    }
    if diag.get("cause") != "injected-dispatch-delay":
        problems.append(
            f"flight: doctor named {diag.get('cause')!r}, expected "
            "'injected-dispatch-delay'"
        )
    if "dispatch delay" not in str(diag.get("probable_cause", "")):
        problems.append("flight: probable_cause text does not name the "
                        "injected dispatch delay")
    if diag.get("rule") != "slo_miss_rate":
        problems.append(
            f"flight: diagnosis rule {diag.get('rule')!r} != "
            "'slo_miss_rate'"
        )
    return problems


def _vault_io_chaos(report: dict) -> list:
    """``io:*`` fault injection against the persistent tier: every
    corruption mode quarantines + rebuilds identically; an injected
    ENOSPC degrades the write, not the pack."""
    import numpy as np

    from sparse_tpu import plan_cache, telemetry as tel, vault
    from sparse_tpu.batch.operator import SparsityPattern
    from sparse_tpu.config import settings
    from sparse_tpu.resilience import faults

    problems = []
    tel.reset()
    vdir = tempfile.mkdtemp(prefix="chaos_vault_io_")
    old_vault = settings.vault
    settings.vault = vdir

    def repack(n):
        """Fresh pattern object + cleared tier 1 => forced disk read."""
        plan_cache.clear()
        return SparsityPattern.from_csr(_tridiag(n)).sell_pack()

    def same(a, b):
        return (
            a is not None and b is not None and a.plan == b.plan
            and np.array_equal(np.asarray(a.pos), np.asarray(b.pos))
        )

    try:
        # A: bitflip-on-read — the stored artifact corrupts in flight
        p0 = SparsityPattern.from_csr(_tridiag(40)).sell_pack()
        base = vault.stats()
        faults.configure("bitflip:io:p=1,seed=5,n=1")
        try:
            p1 = repack(40)
        finally:
            faults.clear()
        st = vault.stats()
        if st["quarantined"] <= base["quarantined"]:
            problems.append("vault io: bitflipped read not quarantined")
        if not same(p0, p1):
            problems.append("vault io: rebuild after bitflip differs")

        # B: truncate-on-write — a torn artifact survives on disk
        faults.configure("truncate:io:p=1,n=1")
        try:
            pb = SparsityPattern.from_csr(_tridiag(48)).sell_pack()
        finally:
            faults.clear()
        base = vault.stats()
        pb2 = repack(48)
        st = vault.stats()
        if st["quarantined"] <= base["quarantined"]:
            problems.append("vault io: truncated artifact not quarantined")
        if not same(pb, pb2):
            problems.append("vault io: rebuild after truncation differs")

        # C: ENOSPC at write — persistence fails, the pack must not
        faults.configure("enospc:io:p=1,n=1")
        base = vault.stats()
        try:
            pc = SparsityPattern.from_csr(_tridiag(56)).sell_pack()
        finally:
            faults.clear()
        st = vault.stats()
        if st["write_failed"] <= base["write_failed"]:
            problems.append("vault io: ENOSPC not counted as write_failed")
        if pc is None:
            problems.append("vault io: ENOSPC broke the pack itself")

        # D: stale-format artifact from an 'older' writer
        faults.configure("stale:io:p=1,n=1")
        try:
            pd = SparsityPattern.from_csr(_tridiag(64)).sell_pack()
        finally:
            faults.clear()
        base = vault.stats()
        pd2 = repack(64)
        st = vault.stats()
        if st["quarantined"] <= base["quarantined"]:
            problems.append("vault io: stale-format artifact not quarantined")
        if not same(pd, pd2):
            problems.append("vault io: rebuild after stale-format differs")

        kinds = _event_kinds(tel)
        if kinds.get("vault.quarantine", 0) == 0:
            problems.append("vault io: no vault.quarantine events")
        if kinds.get("fault.injected", 0) == 0:
            problems.append("vault io: no fault.injected events from io site")
        report["vault_io"] = {"stats": vault.stats(), "events": kinds}
    finally:
        settings.vault = old_vault
        faults.clear()
        plan_cache.clear()
    return problems


def _ingest_arrival(seed, n=32):
    """Deterministic SPD-profile COO arrival (shared by scenario 14's
    parent and subprocess children — same seed => same structure)."""
    import numpy as np

    rng = np.random.default_rng(seed)
    k = 2 * n
    r = rng.integers(0, n, size=k)
    c = rng.integers(0, n, size=k)
    v = 0.1 * rng.standard_normal(k)
    d = np.arange(n)
    rows = np.concatenate([d, r, c])
    cols = np.concatenate([d, c, r])
    vals = np.concatenate([np.full(n, float(n)), v, v])
    return rows, cols, vals, (n, n)


def _ingest_chaos(report: dict) -> list:
    """Scenario 14 part A (ISSUE 18): a seeded loadgen trace mixes
    steady solve traffic with unseen-pattern ``ingest`` arrivals while
    ``truncate:io`` faults tear the vault writes the onboarder makes —
    the solve p95 must hold within the SLO THROUGH onboarding (the PR's
    acceptance criterion), every arrival must still onboard, and a torn
    pattern artifact must quarantine on read-back and rebuild to the
    identical fingerprint from a fresh session (rebuild parity)."""
    import numpy as np

    from sparse_tpu import plan_cache, telemetry as tel, vault
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings
    from sparse_tpu.ingest import structure_key
    from sparse_tpu.loadgen import ArrivalTrace, run_load
    from sparse_tpu.resilience import faults

    problems = []
    tel.reset()
    vdir = tempfile.mkdtemp(prefix="chaos_ingest_")
    old_vault = settings.vault
    settings.vault = vdir
    SLO = 2000.0
    ses = ses2 = None
    try:
        mats, rhs = _vault_traffic()
        ses = SolveSession("cg", slo_ms=SLO, warm_start=False)
        ses.solve_many(mats, rhs, tol=TOL)  # prewarm the serving set
        trace = ArrivalTrace.parse(
            "poisson:rate=30,duration=0.4,seed=4;"
            "ingest:rate=5,duration=0.4,seed=2,size=20"
        )
        faults.configure("truncate:io:p=0.3,seed=9")
        try:
            rep = run_load(ses, trace, list(zip(mats, rhs)), tol=TOL,
                           record=False)
        finally:
            faults.clear()
        kinds = _event_kinds(tel)
        onboard = rep.onboard
        report["ingest_chaos"] = {
            "p95_ms": rep.latency_ms["p95"], "slo_ms": SLO,
            "slo_miss_rate": rep.slo_miss_rate, "onboard": onboard,
            "events": {k: v for k, v in kinds.items()
                       if k.startswith(("ingest.", "fault."))},
        }
        if rep.latency_ms["p95"] > SLO or rep.slo_miss_rate > 0:
            problems.append(
                f"ingest chaos: solve p95 {rep.latency_ms['p95']:.1f}ms "
                f"breached the {SLO:.0f}ms SLO while onboarding ran — "
                "background ingestion leaked onto the serving path"
            )
        if onboard.get("completed", 0) < 1 or onboard.get("failed", 0):
            problems.append(
                f"ingest chaos: onboarding under io faults did not "
                f"complete cleanly ({onboard})"
            )
        if onboard.get("latency_ms", {}).get("p95", 0.0) <= 0.0:
            problems.append(
                "ingest chaos: no separate onboarding latency recorded"
            )
        for kind in ("ingest.arrive", "ingest.sort", "ingest.dedup",
                     "ingest.onboard"):
            if kinds.get(kind, 0) == 0:
                problems.append(f"ingest chaos: no {kind} events")

        # torn-write drill: tear the cold onboard's vault writes, prove
        # quarantine on read-back + fingerprint-identical rebuild
        src = _ingest_arrival(seed=101)
        faults.configure("truncate:io:p=1")  # every onboard write torn
        try:
            t1 = ses.ingest(src, wait=True, timeout=240.0)
        finally:
            faults.clear()
        skey = structure_key(src[0], src[1], src[3])
        pkey = ses._onboarder.index.lookup(skey)
        base_q = vault.stats()["quarantined"]
        torn = vault.load_pattern(pkey) if pkey else None
        quarantined = vault.stats()["quarantined"] > base_q
        ses2 = SolveSession("cg", warm_start=False)
        t2 = ses2.ingest(src, wait=True, timeout=240.0)
        rebuilt = vault.load_pattern(pkey) if pkey else None
        report["ingest_chaos"]["torn"] = {
            "quarantined": bool(quarantined),
            "torn_read": torn is not None,
            "rebuild_fp_match": bool(
                t2.pattern is not None and t1.pattern is not None
                and t2.pattern.fingerprint == t1.pattern.fingerprint
            ),
            "restored": rebuilt is not None,
        }
        if pkey is None:
            problems.append("ingest chaos: onboard noted no pattern key")
        if not quarantined and torn is not None:
            problems.append(
                "ingest chaos: torn pattern artifact served without "
                "quarantine"
            )
        if t2.state != "ready" or t2.pattern.fingerprint != \
                t1.pattern.fingerprint:
            problems.append(
                "ingest chaos: rebuild after torn artifact lost parity "
                f"(state={t2.state})"
            )
        if rebuilt is None or rebuilt.fingerprint != t1.pattern.fingerprint:
            problems.append(
                "ingest chaos: re-onboard did not restore the vaulted "
                "pattern artifact"
            )
    finally:
        for s in (ses, ses2):
            if s is not None and s._onboarder is not None:
                s._onboarder.close()
        settings.vault = old_vault
        faults.clear()
        plan_cache.clear()
    return problems


def _ingest_kill_restart(report: dict) -> list:
    """Scenario 14 part B: an ingest child onboards one arrival into a
    fresh vault, then SIGKILLs itself mid-second-onboarding (partial
    artifacts on disk); a genuinely fresh process must replay the
    vaulted fingerprint index, dedup the re-arrival of the first
    structure, and serve its first solve at ZERO plan-cache misses —
    the restart-surviving half of the dedup acceptance criterion."""
    problems = []
    vdir = tempfile.mkdtemp(prefix="chaos_ingest_kr_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARSE_TPU_VAULT"] = vdir
    env["SPARSE_TPU_COMPILE_CACHE"] = os.path.join(vdir, "_xla_cache")
    env.pop("SPARSE_TPU_FAULTS", None)

    def child(mode):
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--vault-child", mode],
            env=env, capture_output=True, text=True, timeout=300,
        )

    serve = child("ingest-serve")
    if "SERVED" not in serve.stdout:
        problems.append(
            f"ingest restart: serve child never onboarded "
            f"(rc={serve.returncode}, stderr tail: "
            f"{serve.stderr[-300:]!r})"
        )
    elif serve.returncode != -signal.SIGKILL:
        problems.append(
            "ingest restart: serve child was supposed to die by SIGKILL "
            f"mid-onboarding (rc={serve.returncode})"
        )
    warm = child("ingest-warm")
    out = None
    for line in warm.stdout.splitlines():
        if line.startswith("WARM "):
            try:
                out = json.loads(line[5:])
            except json.JSONDecodeError:
                pass
    if out is None:
        problems.append(
            f"ingest restart: warm child produced no report "
            f"(rc={warm.returncode}, stderr tail: {warm.stderr[-300:]!r})"
        )
        return problems
    report["ingest_restart"] = out
    if out.get("index_entries", 0) < 1:
        problems.append(
            "ingest restart: fresh process replayed no fingerprint index"
        )
    if not out.get("dedup", False):
        problems.append(
            "ingest restart: re-arrival of a vaulted structure was not "
            "deduped across the restart"
        )
    d = out.get("delta", {})
    if d.get("misses", 1) != 0:
        problems.append(
            f"ingest restart: deduped re-arrival cost "
            f"{d.get('misses')} plan-cache miss(es) — its first solve "
            "must be a pure hit"
        )
    if not (out.get("resid", 1.0) <= 1e-6):
        problems.append(
            f"ingest restart: deduped solve wrong "
            f"(||r||={out.get('resid'):.2e})"
        )
    return problems


#: scenario 6's traffic shape (shared by parent assertions and children)
VAULT_B = 4
VAULT_N = 64


def _vault_traffic():
    import numpy as np

    rng = np.random.default_rng(21)
    mats = []
    for _ in range(VAULT_B):
        M = _tridiag(VAULT_N)
        M.setdiag(3.0 + rng.random(VAULT_N))
        M.sort_indices()
        mats.append(M.tocsr())
    rhs = rng.standard_normal((VAULT_B, VAULT_N))
    return mats, rhs


def _vault_kill_restart(report: dict) -> list:
    """Scenario 6 parent: child A serves over a fresh vault and SIGKILLs
    itself mid-traffic; child B (a genuinely fresh process) must come
    back warm — manifest replayed, zero plan-cache misses while serving
    the same bucket set, every lane converged."""
    problems = []
    vdir = tempfile.mkdtemp(prefix="chaos_vault_kr_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARSE_TPU_VAULT"] = vdir
    # the XLA-executable tier rides along (ISSUE 9 satellite): both
    # children share one persistent compilation cache dir
    env["SPARSE_TPU_COMPILE_CACHE"] = os.path.join(vdir, "_xla_cache")
    env.pop("SPARSE_TPU_FAULTS", None)

    def child(mode):
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--vault-child", mode],
            env=env, capture_output=True, text=True, timeout=300,
        )

    serve = child("serve")
    if "SERVED" not in serve.stdout:
        problems.append(
            f"vault restart: serve child never served "
            f"(rc={serve.returncode}, stderr tail: "
            f"{serve.stderr[-300:]!r})"
        )
    elif serve.returncode != -signal.SIGKILL:
        problems.append(
            "vault restart: serve child was supposed to die by SIGKILL "
            f"mid-traffic (rc={serve.returncode})"
        )
    warm = child("warm")
    out = None
    for line in warm.stdout.splitlines():
        if line.startswith("WARM "):
            try:
                out = json.loads(line[5:])
            except json.JSONDecodeError:
                pass
    if out is None:
        problems.append(
            f"vault restart: warm child produced no report "
            f"(rc={warm.returncode}, stderr tail: {warm.stderr[-300:]!r})"
        )
        return problems
    report["vault_restart"] = out
    if out.get("replayed", 0) < 1:
        problems.append("vault restart: manifest replayed no programs")
    d = out.get("delta", {})
    if d.get("misses", 1) != 0:
        problems.append(
            f"vault restart: serving window had {d.get('misses')} "
            "plan-cache misses (warm restart must serve on hits only)"
        )
    if d.get("hits", 0) < 1:
        problems.append("vault restart: serving window saw no cache hits")
    if out.get("vault", {}).get("hits", 0) < 1:
        problems.append("vault restart: no disk-tier hits during replay")
    bad = [r for r in out.get("resids", [1.0]) if not (r <= 10 * TOL)]
    if bad:
        problems.append(
            f"vault restart: {len(bad)} lanes unconverged after warm "
            f"restart (worst ||r||={max(bad):.2e})"
        )
    return problems


def _fleet_kill_restart(report: dict) -> list:
    """Scenario 7: the scenario-6 drill under fleet mode. Children run
    with ``SPARSE_TPU_FLEET=auto`` on a forced 8-device virtual CPU
    mesh, so the serve child's bucket programs are mesh-SHARDED and its
    manifest entries carry the mesh fingerprint; the fresh process must
    replay the mesh-keyed manifest back to a zero-serving-miss window."""
    problems = []
    vdir = tempfile.mkdtemp(prefix="chaos_vault_fleet_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()
    env["SPARSE_TPU_VAULT"] = vdir
    env["SPARSE_TPU_COMPILE_CACHE"] = os.path.join(vdir, "_xla_cache")
    env["SPARSE_TPU_FLEET"] = "auto"
    # VAULT_B=4 real lanes must clear the batch-sharding threshold (the
    # bucket then rounds 4 -> 8, one lane per virtual device)
    env["SPARSE_TPU_FLEET_MIN_B"] = "2"
    env.pop("SPARSE_TPU_FAULTS", None)

    def child(mode):
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--vault-child", mode],
            env=env, capture_output=True, text=True, timeout=300,
        )

    serve = child("serve")
    if "SERVED" not in serve.stdout:
        problems.append(
            f"fleet restart: serve child never served "
            f"(rc={serve.returncode}, stderr tail: "
            f"{serve.stderr[-300:]!r})"
        )
    elif serve.returncode != -signal.SIGKILL:
        problems.append(
            "fleet restart: serve child was supposed to die by SIGKILL "
            f"mid-traffic (rc={serve.returncode})"
        )
    warm = child("warm")
    out = None
    for line in warm.stdout.splitlines():
        if line.startswith("WARM "):
            try:
                out = json.loads(line[5:])
            except json.JSONDecodeError:
                pass
    if out is None:
        problems.append(
            f"fleet restart: warm child produced no report "
            f"(rc={warm.returncode}, stderr tail: {warm.stderr[-300:]!r})"
        )
        return problems
    report["fleet_restart"] = out
    meshes = [m for m in out.get("manifest_mesh", []) if m]
    if not meshes:
        problems.append(
            "fleet restart: manifest entries carry no mesh fingerprint "
            "(sharded programs were not noted as mesh-keyed)"
        )
    want_fp = out.get("mesh", {}).get("fingerprint")
    if want_fp and any(m != want_fp for m in meshes):
        problems.append(
            f"fleet restart: manifest mesh {meshes} does not match the "
            f"serving mesh {want_fp!r}"
        )
    if out.get("replayed", 0) < 1:
        problems.append("fleet restart: mesh-keyed manifest replayed no "
                        "programs")
    d = out.get("delta", {})
    if d.get("misses", 1) != 0:
        problems.append(
            f"fleet restart: serving window had {d.get('misses')} "
            "plan-cache misses (mesh-keyed warm restart must serve on "
            "hits only)"
        )
    if d.get("hits", 0) < 1:
        problems.append("fleet restart: serving window saw no cache hits")
    bad = [r for r in out.get("resids", [1.0]) if not (r <= 10 * TOL)]
    if bad:
        problems.append(
            f"fleet restart: {len(bad)} lanes unconverged after warm "
            f"restart (worst ||r||={max(bad):.2e})"
        )
    return problems


def _pipeline_restart_admission(report: dict) -> list:
    """Scenario 10 (ISSUE 13): part A — SIGKILL a pipelined serve child
    with buckets in flight, then prove the fresh process's ASYNC warm
    replay races traffic to a zero-serving-build window; part B — a
    burst under ``max_queue_depth`` emits ``batch.admission`` events
    and the ``queue_depth`` watchdog alert fires during the burst and
    clears after the drain."""
    problems = []
    # -- part A: kill with buckets in flight; async replay serves ----------
    vdir = tempfile.mkdtemp(prefix="chaos_vault_pipe_")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SPARSE_TPU_VAULT"] = vdir
    env["SPARSE_TPU_COMPILE_CACHE"] = os.path.join(vdir, "_xla_cache")
    env["SPARSE_TPU_INFLIGHT"] = "4"
    env.pop("SPARSE_TPU_FAULTS", None)

    def child(mode):
        return subprocess.run(
            [sys.executable, os.path.abspath(__file__),
             "--vault-child", mode],
            env=env, capture_output=True, text=True, timeout=300,
        )

    serve = child("serve-pipe")
    if "SERVED" not in serve.stdout:
        problems.append(
            f"pipeline restart: serve child never served "
            f"(rc={serve.returncode}, stderr tail: "
            f"{serve.stderr[-300:]!r})"
        )
    elif serve.returncode != -signal.SIGKILL:
        problems.append(
            "pipeline restart: serve child was supposed to die by "
            f"SIGKILL with buckets in flight (rc={serve.returncode})"
        )
    warm = child("warm-pipe")
    out = None
    for line in warm.stdout.splitlines():
        if line.startswith("WARM "):
            try:
                out = json.loads(line[5:])
            except json.JSONDecodeError:
                pass
    if out is None:
        problems.append(
            f"pipeline restart: warm child produced no report "
            f"(rc={warm.returncode}, stderr tail: {warm.stderr[-300:]!r})"
        )
    else:
        report["pipeline_restart"] = out
        if out.get("replayed", 0) < 1:
            problems.append(
                "pipeline restart: async replay rebuilt no programs"
            )
        if out.get("serving_builds", 1) != 0:
            problems.append(
                f"pipeline restart: {out.get('serving_builds')} "
                "program(s) built ON the serving path — traffic racing "
                "the async replay must wait for it, not rebuild"
            )
        if out.get("vault", {}).get("hits", 0) < 1:
            problems.append(
                "pipeline restart: no disk-tier hits during replay"
            )
        if out.get("drift", 0) != 0:
            problems.append(
                f"pipeline restart: queue_depth drift "
                f"{out.get('drift')} after serving"
            )
        bad = [r for r in out.get("resids", [1.0]) if not (r <= 10 * TOL)]
        if bad:
            problems.append(
                f"pipeline restart: {len(bad)} lanes unconverged after "
                f"racing warm restart (worst ||r||={max(bad):.2e})"
            )

    # -- part B: burst under max_queue_depth; admission + queue alert ------
    import numpy as np

    from sparse_tpu import telemetry as tel
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.telemetry import _metrics, _watchdog

    tel.reset()
    rng = np.random.default_rng(51)
    mats = []
    for _ in range(4):
        M = _tridiag(N)
        M.setdiag(3.0 + rng.random(N))
        M.sort_indices()
        mats.append(M.tocsr())
    rhs = rng.standard_normal((4, N))

    ses = SolveSession("cg", inflight=2, batch_max=4, max_queue_depth=8,
                       admission="block", warm_start=False)
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()
    bkt = 1
    while bkt <= 4:
        ses._prebuild(pattern, "cg", bkt, np.dtype(np.float64))
        bkt *= 2
    # the queue_depth gauge is process-global: anchor the rule to the
    # depth THIS scenario adds on top of whatever baseline exists
    base = float(_metrics.gauge("batch.queue_depth").value)
    wd = _watchdog.Watchdog(rules=[
        _watchdog.queue_depth_rule(trigger=base + 4.0, clear=base + 1.0),
    ])
    wd.evaluate()
    alerted = False
    for i in range(32):
        ses.submit(mats[i % 4], rhs[i % 4], tol=TOL)
        if ses._unfinalized >= 6:
            wd.evaluate()
            alerted = alerted or "queue_depth" in wd.active()
    ses.drain()
    wd.evaluate()
    cleared = "queue_depth" not in wd.active()
    kinds = _event_kinds(tel)
    drift = ses.session_stats()["tickets"]["queue_depth_drift"]
    report["pipeline_admission"] = {
        "alerted_during_burst": alerted,
        "cleared_after_drain": cleared,
        "admission_events": kinds.get("batch.admission", 0),
        "inflight_events": kinds.get("batch.inflight", 0),
        "drift": drift,
        "tickets": ses.session_stats()["tickets"],
        "events": kinds,
    }
    if kinds.get("batch.admission", 0) < 1:
        problems.append(
            "pipeline admission: burst under max_queue_depth emitted no "
            "batch.admission events"
        )
    if not alerted or kinds.get("watchdog.alert", 0) == 0:
        problems.append(
            "pipeline admission: queue_depth rule did not alert during "
            "the burst"
        )
    if not cleared or kinds.get("watchdog.clear", 0) == 0:
        problems.append(
            "pipeline admission: queue_depth alert did not clear after "
            "the drain"
        )
    if drift != 0:
        problems.append(
            f"pipeline admission: queue_depth gauge drift {drift} != 0"
        )
    done = ses.session_stats()["tickets"]["done"]
    if done != 32:
        problems.append(
            f"pipeline admission: {done}/32 burst tickets resolved"
        )
    return problems


def vault_child(mode: str) -> int:
    """Scenario 6/7/10/16 child entry (``--vault-child
    serve|warm|serve-pipe|warm-pipe|elastic``): reads the vault dir
    from ``SPARSE_TPU_VAULT`` (scenario 7 adds the fleet mode on the
    forced 8-device mesh; scenario 10's ``-pipe`` modes run the
    streaming pipeline — the serve child dies with buckets IN FLIGHT
    and the warm child races traffic against the async replay;
    scenario 16's ``elastic`` mode serves loadgen traffic across a
    live 8->4->8 remesh)."""
    import jax

    jax.config.update("jax_enable_x64", True)
    import numpy as np

    from sparse_tpu import plan_cache, vault
    from sparse_tpu.batch import SolveSession

    mats, rhs = _vault_traffic()
    if mode == "ingest-serve":
        # scenario 14B serve child: onboard one arrival cleanly (vault
        # gets the pattern + fingerprint index), then die by SIGKILL
        # mid-second-onboarding — partial artifacts are the point
        import time

        ses = SolveSession("cg", warm_start=False)
        t = ses.ingest(_ingest_arrival(seed=101), wait=True, timeout=240.0)
        if t.state != "ready":
            return 1
        print("SERVED", flush=True)
        ses.ingest(_ingest_arrival(seed=202))  # background, never waits
        time.sleep(0.05)  # let the worker get INTO the onboard
        os.kill(os.getpid(), signal.SIGKILL)
        return 1  # unreachable
    if mode == "ingest-warm":
        # scenario 14B warm child: a fresh process replays the vaulted
        # fingerprint index; the re-arrival dedups and its first solve
        # is a pure plan-cache hit (zero misses)
        import scipy.sparse as sp

        ses = SolveSession("cg", warm_start=True)
        _ = ses.warm_replayed  # join the async replay before snapshot
        src = _ingest_arrival(seed=101)
        snap = plan_cache.snapshot()
        t = ses.ingest(src, wait=True, timeout=240.0)
        out = t.result()
        n = src[3][0]
        b = np.ones(n)
        tk = ses.submit(out["csr"], b, tol=TOL)
        ses.drain()
        x = np.asarray(tk.result()[0])
        A = sp.csr_matrix(
            (np.asarray(out["csr"].data), np.asarray(out["csr"].indices),
             np.asarray(out["csr"].indptr)), shape=src[3],
        )
        print("WARM " + json.dumps({
            "dedup": bool(out["dedup"]),
            "delta": plan_cache.delta(snap),
            "index_entries": len(ses._onboarder.index),
            "replayed": ses.warm_replayed,
            "resid": float(np.linalg.norm(A @ x - b)),
            "vault": vault.stats(),
        }), flush=True)
        return 0
    if mode == "elastic":
        # scenario 16 child (ISSUE 20): serve loadgen traffic ACROSS a
        # live 8->4->8 topology change, then prove the recovery window
        # serves warm and capture a flight bundle for the doctor
        from sparse_tpu import fleet as fleet_mod
        from sparse_tpu import loadgen, telemetry as tel
        from sparse_tpu.config import settings
        from sparse_tpu.telemetry import _flight

        vdir = os.environ["SPARSE_TPU_VAULT"]
        settings.telemetry = True
        tel.configure(os.path.join(vdir, "_tel.jsonl"))
        ses = SolveSession("cg", warm_start=False)
        # warm the 8-device mesh: its sharded programs are built and
        # vaulted BEFORE the topology starts moving
        ses.solve_many(mats, rhs, tol=TOL)
        trace = (
            loadgen.ArrivalTrace.poisson(rate=40.0, duration=0.6, seed=29)
            + loadgen.ArrivalTrace.remesh_at(0.3, to=4)
        )
        rep = loadgen.run_load(ses, trace, list(zip(mats, rhs)), tol=TOL)
        # a full batch on the shrunken mesh: the 4-device sharded
        # programs are built and vaulted under THEIR fingerprint
        ses.solve_many(mats, rhs, tol=TOL)
        # manual recovery: regain the 8-device mesh, then prove the
        # post-recovery serving window runs on plan-cache hits only
        # (warm replay, zero serving builds)
        snap = plan_cache.snapshot()
        rec = ses.remesh(fleet_mod.fleet_mesh(8))
        X, _i, _r = ses.solve_many(mats, rhs, tol=TOL)
        resids = [
            float(np.linalg.norm(m @ x - b))
            for m, x, b in zip(mats, X, rhs)
        ]
        delta = plan_cache.delta(snap)
        stats = ses.session_stats()
        _flight.stop_flight()
        fr = _flight.flight(root=os.path.join(vdir, "_incidents"))
        bundle = fr.capture(reason="manual")
        _flight.stop_flight()
        print("ELASTIC " + json.dumps({
            "report": {
                "arrivals": rep.arrivals, "completed": rep.completed,
                "failed": rep.failed, "remeshes": rep.remeshes,
            },
            "drift": stats["tickets"]["queue_depth_drift"],
            "recover": rec,
            "mesh": stats.get("mesh", {}),
            "elastic": stats.get("elastic", {}),
            "manifest_mesh": [
                e.get("mesh") for e in vault.manifest_entries()
            ],
            "delta": delta,
            "resids": resids,
            "bundle": bundle,
        }), flush=True)
        return 0
    if mode == "serve":
        ses = SolveSession("cg", warm_start=False)
        ses.solve_many(mats, rhs, tol=TOL)
        print("SERVED", flush=True)
        # resubmit the same traffic and die mid-serving — the crash the
        # vault exists to survive (no flush: requests are in flight)
        for A, b in zip(mats, rhs):
            ses.submit(A, b, tol=TOL)
        os.kill(os.getpid(), signal.SIGKILL)
        return 1  # unreachable
    if mode == "serve-pipe":
        ses = SolveSession("cg", warm_start=False)
        ses.solve_many(mats, rhs, tol=TOL)
        print("SERVED", flush=True)
        # resubmit and dispatch WITHOUT draining: bucket programs are
        # genuinely in flight on the device at the moment of death
        for A, b in zip(mats, rhs):
            ses.submit(A, b, tol=TOL)
        ses.flush(wait=False)
        os.kill(os.getpid(), signal.SIGKILL)
        return 1  # unreachable
    if mode == "warm-pipe":
        # async warm replay (the default) racing immediate traffic: the
        # dispatch path must WAIT for the replay's programs, so the
        # serving path builds nothing
        ses = SolveSession("cg", warm_start=True)
        tickets = [ses.submit(A, b, tol=TOL) for A, b in zip(mats, rhs)]
        ses.flush(wait=False)
        X = [t.result()[0] for t in tickets]
        resids = [
            float(np.linalg.norm(m @ np.asarray(x) - b))
            for m, x, b in zip(mats, X, rhs)
        ]
        stats = ses.session_stats()
        print("WARM " + json.dumps({
            "replayed": ses.warm_replayed,
            "serving_builds": stats["pipeline"]["serving_builds"],
            "drift": stats["tickets"]["queue_depth_drift"],
            "resids": resids,
            "vault": vault.stats(),
        }), flush=True)
        return 0
    ses = SolveSession("cg", warm_start=True)
    # scenarios 6/7 measure the steady WARM serving window, so join the
    # (now asynchronous, ISSUE 13) replay before snapshotting — the
    # replay-vs-traffic race itself is scenario 10's drill
    _ = ses.warm_replayed
    snap = plan_cache.snapshot()
    X, _iters, _r2 = ses.solve_many(mats, rhs, tol=TOL)
    resids = [
        float(np.linalg.norm(m @ x - b)) for m, x, b in zip(mats, X, rhs)
    ]
    print("WARM " + json.dumps({
        "replayed": ses.warm_replayed,
        "delta": plan_cache.delta(snap),
        "resids": resids,
        "vault": vault.stats(),
        # scenario 7 evidence: which mesh fingerprints the manifest
        # carries and what mesh this process actually served on
        "manifest_mesh": [
            e.get("mesh") for e in vault.manifest_entries()
        ],
        "mesh": ses.session_stats().get("mesh", {}),
    }), flush=True)
    return 0


def main(argv) -> int:
    if "--vault-child" in argv:
        i = argv.index("--vault-child")
        return vault_child(argv[i + 1] if i + 1 < len(argv) else "serve")
    report: dict = {}
    from sparse_tpu import telemetry as tel
    from sparse_tpu.config import settings

    old_tel = settings.telemetry
    sink = tempfile.NamedTemporaryFile(
        suffix=".jsonl", prefix="chaos_", delete=False
    )
    sink.close()
    settings.telemetry = True
    tel.configure(sink.name)
    try:
        problems = run(report)
    finally:
        settings.telemetry = old_tel
        tel.configure(None)
        tel.reset()
        try:
            os.unlink(sink.name)
        except OSError:
            pass
    if "--json" in argv:
        print(json.dumps(report, indent=1, default=str))
    for p in problems:
        print(f"CHAOS FAILURE: {p}", file=sys.stderr)
    if not problems:
        vr = report.get("vault_restart", {})
        fr = report.get("fleet_restart", {})
        lw = report.get("loadgen_watchdog", {})
        fl = report.get("incident_flight", {})
        pr = report.get("pipeline_restart", {})
        pa = report.get("pipeline_admission", {})
        mp = report.get("mixed_promote", {})
        mw = report.get("mixed_warm_restart", {})
        ac = report.get("autopilot_chaos", {})
        ig = report.get("ingest_chaos", {})
        ir = report.get("ingest_restart", {})
        bb = report.get("budget_burn", {})
        el = report.get("elastic_remesh", {})
        print(
            "chaos check passed: "
            f"{len([k for k in report if k.startswith('solver.')])} solvers "
            "recovered, pallas failover+reinstate ok, "
            f"batch lanes ok, {report.get('preempt', {}).get('resumes', 0)} "
            "preemption resume(s), vault io quarantines ok, "
            f"kill-and-restart warm ({vr.get('replayed', 0)} program(s) "
            f"replayed, {vr.get('delta', {}).get('misses', '?')} serving "
            f"misses), fleet restart warm ({fr.get('replayed', 0)} "
            f"mesh-keyed program(s), {fr.get('delta', {}).get('misses', '?')} "
            "serving misses), watchdog alert->clear ok (faulted "
            f"slo_miss_rate={lw.get('faulted', {}).get('slo_miss_rate', '?')}"
            " -> clean "
            f"{lw.get('clean', {}).get('slo_miss_rate', '?')}), "
            f"incident flight ok ({len(fl.get('bundles', []))} bundle, "
            f"{fl.get('suppressed', '?')} suppressed, doctor cause "
            f"{fl.get('diagnosis', {}).get('cause', '?')!r}), "
            f"pipeline restart ok ({pr.get('replayed', 0)} async-replayed "
            f"program(s), {pr.get('serving_builds', '?')} serving "
            f"build(s)), admission burst ok "
            f"({pa.get('admission_events', 0)} admission event(s), "
            f"queue alert fired+cleared, drift {pa.get('drift', '?')}), "
            f"mixed promote_dtype ok ({mp.get('promotions', 0):.0f} "
            "promotion(s), converged at exact), mixed warm restart "
            f"({mw.get('replayed', 0)} precision-keyed program(s), "
            f"{mw.get('serving_misses', '?')} serving misses), "
            f"autopilot drift->reopen->reconverge ok "
            f"({ac.get('drift_strikes', 0):.0f} strike(s), re-pinned "
            f"{ac.get('reconverged', {}).get('arm', '?')!r}, restart "
            f"restored={ac.get('restart', {}).get('restored', '?')}), "
            f"ingest chaos ok (solve p95 "
            f"{ig.get('p95_ms', '?')}ms under SLO through "
            f"{ig.get('onboard', {}).get('completed', 0)} onboard(s), "
            f"torn artifact quarantined="
            f"{ig.get('torn', {}).get('quarantined', '?')}, restart dedup="
            f"{ir.get('dedup', '?')} at "
            f"{ir.get('delta', {}).get('misses', '?')} serving misses), "
            f"error-budget burn page->clear ok "
            f"({bb.get('bundle_history_points', '?')} history point(s) in "
            f"the bundle, doctor rule "
            f"{bb.get('diagnosis', {}).get('rule', '?')!r}), "
            f"elastic remesh ok "
            f"({el.get('report', {}).get('completed', 0)} ticket(s) "
            "terminal across 8->4->8, "
            f"{el.get('delta', {}).get('misses', '?')} recovery misses, "
            f"doctor cause "
            f"{el.get('diagnosis', {}).get('cause', '?')!r})"
        )
    return 1 if problems else 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
