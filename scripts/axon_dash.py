#!/usr/bin/env python
"""Terminal sparkline dashboard over the Axon v7 history segments.

Usage:
    python scripts/axon_dash.py [--root DIR] [--window 300] [--res 0]
                                [--series SUBSTR[,SUBSTR...]]
                                [--limit 40] [--interval 2] [--once]

Renders the continuous-telemetry history store
(``telemetry/_history.py`` — ``SPARSE_TPU_HISTORY=1``) as one unicode
sparkline row per metric series: name, spark of the window, last value,
min/max. Pure stdlib and **reads the on-disk segments directly** (no
sparse_tpu import): it works on a live session's directory, after the
process died, and on a directory copied off another machine. The live
exporter's ``/dash`` page is the in-process variant of the same board.

    --root      segments directory (default: SPARSE_TPU_HISTORY_DIR,
                else results/axon/history next to this repo)
    --window    seconds of history to show (default 300)
    --res       resolution: 0 = raw samples, 10/60 = rollups (the
                min/max/mean/last rollup plots its mean) (default 0)
    --series    comma-separated substring filters (default: the serving
                headline series; pass '' for everything)
    --limit     max rows (default 40)
    --interval  refresh period in seconds (default 2)
    --once      render one frame and exit (the smoke-test mode)

Exit codes: 0 = rendered (even an empty directory renders a header),
2 = bad usage.
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_ROOT = os.path.join(REPO, "results", "axon", "history")

SPARK = "▁▂▃▄▅▆▇█"
#: default headline filters — the serving-path series an operator
#: watches first (same set as the exporter's /dash)
DEFAULT_SERIES = (
    "batch.ticket_latency",
    "batch.slo_misses",
    "batch.queue_depth",
    "batch.dispatches",
    "usage.",
)


def read_segments(root: str, res: int | None = None) -> list:
    """Parse every committed segment under ``root`` (stdlib mirror of
    ``_history.read_segments``): skips files whose header line is not a
    v1 ``history.segment``, keeps the intact prefix of a torn tail,
    returns points sorted by (t, r)."""
    points = []
    try:
        names = sorted(
            n for n in os.listdir(root)
            if n.startswith("seg-") and n.endswith(".jsonl")
        )
    except OSError:
        return points
    for name in names:
        try:
            with open(os.path.join(root, name)) as f:
                lines = f.read().splitlines()
        except OSError:
            continue
        if not lines:
            continue
        try:
            head = json.loads(lines[0])
        except (json.JSONDecodeError, ValueError):
            continue
        if head.get("kind") != "history.segment" or head.get("format") != 1:
            continue
        session = head.get("session")
        for ln in lines[1:]:
            try:
                p = json.loads(ln)
            except (json.JSONDecodeError, ValueError):
                break  # torn tail: keep the intact prefix
            if not isinstance(p, dict) or "t" not in p or "s" not in p:
                break
            if res is not None and p.get("r", 0) != res:
                continue
            p["session"] = session
            points.append(p)
    points.sort(key=lambda p: (p.get("t", 0.0), p.get("r", 0)))
    return points


def sparkline(values: list) -> str:
    vals = [float(v) for v in values if isinstance(v, (int, float))]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = hi - lo
    if span <= 0:
        return SPARK[0] * len(vals)
    return "".join(
        SPARK[min(int((v - lo) / span * (len(SPARK) - 1) + 0.5),
                  len(SPARK) - 1)]
        for v in vals
    )


def _fmt(v: float) -> str:
    if isinstance(v, float) and v != int(v):
        return f"{v:.4g}"
    return str(int(v)) if isinstance(v, (int, float)) else str(v)


def render(root: str, window_s: float, res: int, filters: tuple,
           limit: int, width: int = 60) -> str:
    """One frame: header + a sparkline row per matching series."""
    points = read_segments(root, res=res)
    now = points[-1]["t"] if points else time.time()
    points = [p for p in points if p["t"] >= now - window_s]
    sessions = sorted({p.get("session") for p in points if p.get("session")})
    keys = sorted({k for p in points for k in p.get("s", {})})
    if filters:
        keys = [k for k in keys if any(s in k for s in filters)] or keys
    lines = [
        f"axon dash · {root}",
        f"window {int(window_s)}s · res {res} · {len(points)} points · "
        f"{len(sessions)} session(s) · "
        + time.strftime("%H:%M:%S", time.localtime(now)),
        "",
    ]
    if not points:
        lines.append("(no history points — is SPARSE_TPU_HISTORY set and "
                     "a session running?)")
        return "\n".join(lines) + "\n"
    name_w = min(max((len(k) for k in keys[:limit]), default=10), 42)
    for k in keys[:limit]:
        series = []
        for p in points:
            v = p["s"].get(k)
            if isinstance(v, list):  # rollup [min,max,mean,last] -> mean
                v = v[2] if len(v) == 4 else None
            if isinstance(v, (int, float)):
                series.append(v)
        if not series:
            continue
        tail = series[-width:]
        lines.append(
            f"{k[:name_w]:<{name_w}} {sparkline(tail):<{width}} "
            f"last={_fmt(series[-1])} min={_fmt(min(series))} "
            f"max={_fmt(max(series))}"
        )
    dropped = len(keys) - limit
    if dropped > 0:
        lines.append(f"... {dropped} more series (--limit to raise, "
                     "--series to filter)")
    return "\n".join(lines) + "\n"


def main(argv) -> int:
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    args = list(argv)
    once = "--once" in args
    if once:
        args.remove("--once")

    def take(flag, default):
        if flag in args:
            i = args.index(flag)
            if i + 1 >= len(args):
                print(f"axon_dash: {flag} needs a value", file=sys.stderr)
                raise SystemExit(2)
            v = args[i + 1]
            del args[i:i + 2]
            return v
        return default

    root = take("--root", os.environ.get("SPARSE_TPU_HISTORY_DIR")
                or DEFAULT_ROOT)
    try:
        window_s = float(take("--window", "300"))
        res = int(take("--res", "0"))
        limit = int(take("--limit", "40"))
        interval = float(take("--interval", "2"))
    except ValueError:
        print("axon_dash: --window/--res/--limit/--interval must be "
              "numeric", file=sys.stderr)
        return 2
    if res not in (0, 10, 60):
        print("axon_dash: --res must be 0, 10 or 60", file=sys.stderr)
        return 2
    series = take("--series", None)
    filters = (
        tuple(s for s in series.split(",") if s) if series is not None
        else DEFAULT_SERIES
    )
    if args:
        print(f"axon_dash: unknown arguments {args}", file=sys.stderr)
        return 2

    if once:
        sys.stdout.write(render(root, window_s, res, filters, limit))
        return 0
    try:
        while True:
            frame = render(root, window_s, res, filters, limit)
            sys.stdout.write("\x1b[2J\x1b[H" + frame)
            sys.stdout.flush()
            time.sleep(interval)
    except KeyboardInterrupt:
        print()
    return 0


if __name__ == "__main__":
    raise SystemExit(main(sys.argv[1:]))
