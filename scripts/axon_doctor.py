#!/usr/bin/env python
"""Incident bundle analyzer: rule-based probable-cause diagnosis.

The flight recorder (``sparse_tpu/telemetry/_flight.py``) captures a
postmortem bundle at the moment a watchdog rule fires; this script turns
a bundle into a *diagnosis* — the triggering alert joined with the event
chains in the ring tail against a table of known failure signatures
(docs/telemetry.md "Axon v6" wires the same table into the operator
runbook):

* ``slo_miss_rate`` + a ``fault.injected`` ``delay:dispatch`` chain
  → "injected dispatch delay";
* ``slo_fast_burn``/``slo_slow_burn`` (the v7 error-budget watchdog)
  → "error budget burning at Nx", joined with the bundle's embedded
  ``history.json`` window to say when the misses *started*;
* latched failovers / ``kernel.failover`` events → "Pallas kernel
  failed over to XLA";
* ``vault.quarantine`` events → "vault artifact corruption";
* ``plan_cache.compile`` events inside the breach window →
  "compile tax in the serving window"; ... (the ``_DIAGNOSES`` table is
  the authoritative list).

Usage:
    python scripts/axon_doctor.py [BUNDLE | INCIDENTS_ROOT] [--json] [--quiet]

With no argument the newest bundle under ``results/axon/incidents/`` is
analyzed; a root directory resolves to its newest bundle. ``--json``
prints the machine diagnosis (``probable_cause``, ``evidence``,
``matches``) — what chaos scenario 9 asserts against.

Exit codes: 0 = diagnosed (including "unknown"), 2 = no bundle found /
unreadable manifest.

Pure-stdlib on purpose, like ``axon_report.py``: no sparse_tpu import,
no jax init — a paged operator (or CI) runs it in milliseconds against
files already on disk.
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
DEFAULT_ROOT = os.path.join(REPO, "results", "axon", "incidents")


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------
def resolve_bundle(path: str | None) -> str | None:
    """A bundle dir (has ``incident.json``), or the newest bundle under
    a root dir; ``None`` when nothing resolves."""
    path = path or DEFAULT_ROOT
    if os.path.isfile(os.path.join(path, "incident.json")):
        return path
    if not os.path.isdir(path):
        return None
    for name in sorted(os.listdir(path), reverse=True):
        cand = os.path.join(path, name)
        if os.path.isfile(os.path.join(cand, "incident.json")):
            return cand
    return None


def load_bundle(bundle: str) -> tuple:
    """(manifest dict, ring events list); tolerant of partial bundles —
    a missing/corrupt ring still diagnoses from the manifest alone."""
    try:
        manifest = json.load(open(os.path.join(bundle, "incident.json")))
    except (OSError, json.JSONDecodeError, ValueError):
        return None, []
    events = []
    try:
        with open(os.path.join(bundle, "ring.jsonl")) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    ev = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if isinstance(ev, dict) and "kind" in ev:
                    events.append(ev)
    except OSError:
        pass
    return manifest if isinstance(manifest, dict) else None, events


def load_history(bundle: str) -> dict | None:
    """The bundle's embedded ``history.json`` time-series window (only
    present when the v7 history sampler was live at capture)."""
    try:
        h = json.load(open(os.path.join(bundle, "history.json")))
        return h if isinstance(h, dict) else None
    except (OSError, json.JSONDecodeError, ValueError):
        return None


def _burn_onset(history: dict | None) -> float | None:
    """When the SLO misses *started* accumulating inside the bundle's
    history window: the first raw sample whose ``batch.slo_misses``
    counter moved off the window's base value. None without a usable
    series — the diagnosis degrades to alert-time evidence only."""
    if not history:
        return None
    series = []
    for p in history.get("points", []):
        if p.get("r", 0) != 0:
            continue
        v = (p.get("s") or {}).get("batch.slo_misses")
        if isinstance(v, (int, float)) and isinstance(
            p.get("t"), (int, float)
        ):
            series.append((p["t"], v))
    if len(series) < 2:
        return None
    base = series[0][1]
    for t, v in series:
        if v > base:
            return t
    return None


# ---------------------------------------------------------------------------
# evidence summaries
# ---------------------------------------------------------------------------
def _summarize(manifest: dict, events: list,
               history: dict | None = None) -> dict:
    """The joined evidence picture every diagnosis rule matches on."""
    kinds: dict = {}
    faults_by: dict = {}  # (site, fault) -> count
    anomaly_reasons: dict = {}
    failover_kernels = set()
    quarantine_reasons: dict = {}
    burn_tenant = None
    remeshes: list = []  # fleet.remesh / fleet.remesh_failed rows
    remesh_requeues = 0  # lanes migrated through remesh requeue chains
    mesh_skipped = 0  # manifest entries skipped on topology mismatch
    for e in events:
        k = str(e.get("kind", "?"))
        kinds[k] = kinds.get(k, 0) + 1
        if k == "fault.injected":
            key = (str(e.get("site", "?")), str(e.get("fault", "?")))
            faults_by[key] = faults_by.get(key, 0) + 1
        elif k == "solver.anomaly":
            r = str(e.get("reason", "?"))
            anomaly_reasons[r] = anomaly_reasons.get(r, 0) + 1
        elif k == "kernel.failover":
            failover_kernels.add(str(e.get("kernel", "?")))
        elif k == "vault.quarantine":
            r = str(e.get("reason", "?"))
            quarantine_reasons[r] = quarantine_reasons.get(r, 0) + 1
        elif k == "budget.burn":
            burn_tenant = e.get("tenant")  # latest wins
        elif k in ("fleet.remesh", "fleet.remesh_failed"):
            remeshes.append(e)
        elif k == "batch.requeue" and e.get("action") == "remesh":
            try:
                remesh_requeues += int(e.get("lanes", 0))
            except (TypeError, ValueError):
                pass
        elif k == "vault.replay":
            try:
                mesh_skipped += int(e.get("mesh_skipped", 0))
            except (TypeError, ValueError):
                pass
    # the flight bundle embeds the remesh transition directly (manifest
    # 'remesh' block) — union with ring evidence so a tail too short to
    # hold the events still diagnoses
    for e in manifest.get("remesh") or ():
        if isinstance(e, dict) and e not in remeshes:
            remeshes.append(e)
    trans = manifest.get("transition") or {}
    latches = manifest.get("failover_latches") or {}
    faults_cfg = manifest.get("faults") or {}
    return {
        "rule": str(manifest.get("rule") or trans.get("rule") or ""),
        "severity": str(trans.get("severity") or ""),
        "value": trans.get("value"),
        "trigger": trans.get("trigger"),
        "kinds": kinds,
        "faults_by": faults_by,
        "faults_active": bool(faults_cfg.get("active")),
        "faults_spec": str(faults_cfg.get("spec") or ""),
        "anomaly_reasons": anomaly_reasons,
        "failover_kernels": sorted(failover_kernels),
        "latches": latches,
        "quarantine_reasons": quarantine_reasons,
        "compiles": kinds.get("plan_cache.compile", 0),
        "deadlines": kinds.get("batch.deadline", 0),
        "degraded": kinds.get("batch.degraded", 0),
        "requeues": kinds.get("batch.requeue", 0),
        "remeshes": remeshes,
        "remesh_requeues": remesh_requeues,
        "mesh_skipped": mesh_skipped,
        "burn_tenant": burn_tenant,
        "burn_onset_t": _burn_onset(history),
        "capture_ts": manifest.get("ts"),
    }


# ---------------------------------------------------------------------------
# the diagnosis table (ordered: first match is the probable cause)
# ---------------------------------------------------------------------------
def _d_injected_delay(s):
    n = s["faults_by"].get(("dispatch", "delay"), 0)
    if not n:
        return None
    ev = [f"{n} fault.injected event(s) with site=dispatch fault=delay"]
    if s["faults_spec"]:
        ev.append(f"fault spec at capture: {s['faults_spec']!r}")
    return ("injected dispatch delay (resilience.faults "
            "delay:dispatch clause)", ev,
            "clear SPARSE_TPU_FAULTS / faults.clear(); latency recovers "
            "with the next clean dispatches")


def _d_injected_drop(s):
    n = s["faults_by"].get(("dispatch", "drop"), 0)
    if not n:
        return None
    return ("injected dispatch drops (resilience.faults "
            "drop:dispatch clause)",
            [f"{n} fault.injected event(s) with site=dispatch fault=drop"],
            "clear the fault spec; dispatch_attempts retries absorb "
            "transient drops")


def _d_injected_matvec(s):
    n = sum(v for (site, _f), v in s["faults_by"].items()
            if site == "matvec")
    if not n:
        return None
    return ("injected matvec corruption (resilience.faults matvec "
            "clause)",
            [f"{n} fault.injected event(s) at site=matvec",
             f"anomalies: {s['anomaly_reasons']}" if s["anomaly_reasons"]
             else "recovery engine chains expected (solver.retry)"],
            "clear the fault spec; solve_with_recovery's ladder handles "
            "live corruption")


def _d_injected_io(s):
    n = sum(v for (site, _f), v in s["faults_by"].items() if site == "io")
    if not n:
        return None
    return ("injected vault io faults (resilience.faults io clause)",
            [f"{n} fault.injected event(s) at site=io"],
            "clear the fault spec; verify-then-load quarantines and "
            "rebuilds")


def _d_remesh(s):
    rows = s["remeshes"]
    if not rows:
        return None
    ok = [e for e in rows if str(e.get("kind")) == "fleet.remesh"]
    failed = [e for e in rows if str(e.get("kind")) == "fleet.remesh_failed"]
    # name the transition by the last executed remesh (latest wins); a
    # latched flap guard with no executed transition still diagnoses
    last = ok[-1] if ok else rows[-1]
    old = str(last.get("old", "?"))
    new = str(last.get("new", "?"))
    if ok:
        cause = (f"mesh topology change: fleet re-planned from "
                 f"{old} to {new} "
                 f"(reason={last.get('reason', '?')})")
    else:
        cause = (f"mesh topology flapping: remesh flap guard latched "
                 f"on {old}, session pinned to the single strategy")
    ev = [f"{len(ok)} fleet.remesh event(s)"
          + (f", {len(failed)} fleet.remesh_failed" if failed else "")]
    if s["remesh_requeues"]:
        ev.append(
            f"requeue chain migrated {s['remesh_requeues']} in-flight "
            "lane(s) with best-iterate x0 (batch.requeue action=remesh)")
    if s["mesh_skipped"]:
        ev.append(
            f"vault replay skipped {s['mesh_skipped']} manifest "
            "entr(ies) keyed to the departed mesh")
    replayed = sum(int(e.get("replayed", 0) or 0) for e in ok)
    if replayed:
        ev.append(f"{replayed} plan(s) replayed warm from the "
                  "mesh-keyed vault manifest")
    if failed:
        return (cause, ev,
                "topology is oscillating: stabilise the device fleet, "
                "then session.remesh(mesh) to unpin; raise "
                "SPARSE_TPU_REMESH_RETRIES only if flaps are expected")
    return (cause, ev,
            "expected after a slice loss/regain; verify tickets all "
            "reached terminal states and gauges read zero "
            "(docs/resilience.md \"Elastic topology\")")


def _d_failover(s):
    if not s["latches"] and not s["failover_kernels"]:
        return None
    ev = []
    if s["latches"]:
        ev.append(f"latched failovers at capture: {s['latches']}")
    if s["failover_kernels"]:
        ev.append(
            "kernel.failover event(s) for: "
            + ", ".join(s["failover_kernels"])
        )
    return ("Pallas kernel failed over to the XLA formulation",
            ev,
            "results stay correct on the fallback; probe_pallas() "
            "reinstates after the underlying failure clears "
            "(docs/resilience.md)")


def _d_vault(s):
    n = s["kinds"].get("vault.quarantine", 0)
    if not n and s["rule"] != "vault_quarantine":
        return None
    ev = [f"{n} vault.quarantine event(s)"]
    if s["quarantine_reasons"]:
        ev.append(f"verify failures: {s['quarantine_reasons']}")
    return ("vault artifact corruption (disk tier quarantining)",
            ev,
            "inspect <vault>/quarantine/; rebuilds are automatic, "
            "recurring checksum failures mean bad storage")


def _d_burn(s):
    if s["rule"] not in ("slo_fast_burn", "slo_slow_burn"):
        return None
    speed = "fast" if s["rule"] == "slo_fast_burn" else "slow"
    ev = [
        f"error budget burning at {s['value']}x the sustainable rate "
        f"({speed} windows, page/warn trigger {s['trigger']}x)"
    ]
    if s["burn_tenant"]:
        ev.append(f"worst tenant at breach: {s['burn_tenant']!r}")
    onset = s["burn_onset_t"]
    if isinstance(onset, (int, float)):
        iso = time.strftime("%H:%M:%SZ", time.gmtime(onset))
        ago = (
            f" ({s['capture_ts'] - onset:.0f}s before capture)"
            if isinstance(s["capture_ts"], (int, float)) else ""
        )
        ev.append(
            f"history window: SLO misses started accumulating at "
            f"{iso}{ago}"
        )
    return (f"SLO error budget {speed}-burning — misses are consuming "
            "the budget faster than the objective sustains",
            ev,
            "fast burn pages (minutes to exhaustion), slow burn warns "
            "(days): find the onset in the bundle's history.json, then "
            "the cause in the secondary matches below "
            "(docs/telemetry.md 'Axon v7')")


def _d_queue(s):
    if s["rule"] != "queue_depth":
        return None
    ev = [f"queue_depth {s['value']} breached trigger {s['trigger']}"]
    if s["deadlines"]:
        ev.append(f"{s['deadlines']} batch.deadline expiry event(s)")
    return ("arrivals outrunning dispatch capacity (queue saturation)",
            ev,
            "raise batch_max / add mesh capacity (SPARSE_TPU_FLEET), or "
            "shed load via per-ticket deadlines")


def _d_occupancy(s):
    if s["rule"] != "device_occupancy":
        return None
    return ("mesh underutilized in dispatching windows (occupancy "
            "floor)",
            [f"mean occupancy {s['value']} under floor {s['trigger']}"],
            "traffic too ragged for the bucket geometry: check "
            "SPARSE_TPU_FLEET_MIN_B and bucket pad waste in "
            "batch.dispatch events")


def _d_degraded(s):
    if not s["degraded"]:
        return None
    return ("compiled bucket path unavailable — serving on per-lane "
            "eager fallback",
            [f"{s['degraded']} batch.degraded event(s)"],
            "check the degradation reasons on the events; eager lanes "
            "are orders slower than the compiled path")


def _d_anomalies(s):
    if s["rule"] != "anomaly_rate" and not s["anomaly_reasons"]:
        return None
    return ("solver anomalies detected "
            f"({', '.join(sorted(s['anomaly_reasons'])) or 'see rule'})",
            [f"solver.anomaly reasons: {s['anomaly_reasons']}"],
            "nonfinite/breakdown lanes requeue automatically; persistent "
            "stagnation means tol/maxiter or preconditioning "
            "(docs/resilience.md anomaly table)")


def _d_compile_tax(s):
    if s["rule"] not in (
        "slo_miss_rate", "slo_fast_burn", "slo_slow_burn"
    ) or not s["compiles"]:
        return None
    return ("compile tax inside the serving window (cold buckets "
            "breached the SLO)",
            [f"{s['compiles']} plan_cache.compile event(s) in the ring "
             "tail alongside the latency breach"],
            "enable SPARSE_TPU_VAULT warm restart (+ "
            "SPARSE_TPU_COMPILE_CACHE) or prebuild the traffic's "
            "buckets")


def _d_slo_unattributed(s):
    if s["rule"] != "slo_miss_rate":
        return None
    return ("serving latency breach with no fault/compile evidence in "
            "the captured window",
            [f"slo_miss_rate {s['value']} over trigger {s['trigger']}"],
            "inspect trace.json ticket waterfalls for the slow phase "
            "(queue wait = capacity, solve = workload shift)")


#: ordered (id, matcher) — first hit is THE probable cause, later hits
#: are reported as secondary matches
_DIAGNOSES = (
    ("injected-dispatch-delay", _d_injected_delay),
    ("injected-dispatch-drop", _d_injected_drop),
    ("injected-matvec-corruption", _d_injected_matvec),
    ("injected-io-fault", _d_injected_io),
    ("mesh-topology-change", _d_remesh),
    ("pallas-failover", _d_failover),
    ("vault-corruption", _d_vault),
    ("slo-error-budget-burn", _d_burn),
    ("queue-saturation", _d_queue),
    ("occupancy-floor", _d_occupancy),
    ("degraded-serving", _d_degraded),
    ("solver-anomalies", _d_anomalies),
    ("compile-tax", _d_compile_tax),
    ("slo-breach-unattributed", _d_slo_unattributed),
)


def diagnose(manifest: dict, events: list,
             history: dict | None = None) -> dict:
    """The machine diagnosis of one bundle: the first matching signature
    is ``probable_cause``; every other match lands in ``matches`` (an
    incident can have several true findings — an injected delay AND the
    resulting requeues)."""
    s = _summarize(manifest, events, history)
    matches = []
    for did, fn in _DIAGNOSES:
        try:
            hit = fn(s)
        except Exception:  # noqa: BLE001 - one matcher never kills the run
            hit = None
        if hit:
            cause, evidence, runbook = hit
            matches.append({
                "id": did,
                "cause": cause,
                "evidence": [e for e in evidence if e],
                "runbook": runbook,
            })
    primary = matches[0] if matches else {
        "id": "unknown",
        "cause": "no known failure signature in the captured window",
        "evidence": [f"ring kinds: {s['kinds']}"],
        "runbook": "read ring.jsonl / trace.json directly; consider a "
        "/debug/capture profile while the incident is live",
    }
    return {
        "rule": s["rule"],
        "severity": s["severity"],
        "value": s["value"],
        "trigger": s["trigger"],
        "cause": primary["id"],
        "probable_cause": primary["cause"],
        "evidence": primary["evidence"],
        "runbook": primary["runbook"],
        "matches": matches,
        "events": len(events),
        "events_by_kind": dict(sorted(s["kinds"].items())),
    }


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------
def _print_diagnosis(bundle: str, manifest: dict, diag: dict) -> None:
    proc = manifest.get("process") or {}
    print(f"axon_doctor: {os.path.basename(bundle)}")
    print(
        f"  captured {manifest.get('iso', '?')} by process "
        f"pi={proc.get('pi', '?')} pid={proc.get('pid', '?')} "
        f"({manifest.get('reason', '?')})"
    )
    if diag["rule"]:
        print(
            f"  alert: {diag['rule']} [{diag['severity'] or '?'}] "
            f"value={diag['value']} trigger={diag['trigger']}"
        )
    print(f"  PROBABLE CAUSE [{diag['cause']}]: {diag['probable_cause']}")
    for e in diag["evidence"]:
        print(f"    evidence: {e}")
    print(f"    runbook: {diag['runbook']}")
    for m in diag["matches"][1:]:
        print(f"  also [{m['id']}]: {m['cause']}")
    if diag["events_by_kind"]:
        print(f"  ring tail ({diag['events']} events):")
        for k, n in diag["events_by_kind"].items():
            print(f"    {k:<22} {n}")


def main(argv) -> int:
    if "--help" in argv or "-h" in argv:
        print(__doc__)
        return 0
    args = list(argv)
    as_json = "--json" in args
    if as_json:
        args.remove("--json")
    quiet = "--quiet" in args
    if quiet:
        args.remove("--quiet")
    bundle = resolve_bundle(args[0] if args else None)
    if bundle is None:
        print(
            f"axon_doctor: no incident bundle under "
            f"{args[0] if args else DEFAULT_ROOT}",
            file=sys.stderr,
        )
        return 2
    manifest, events = load_bundle(bundle)
    if manifest is None:
        print(
            f"axon_doctor: unreadable manifest in {bundle}",
            file=sys.stderr,
        )
        return 2
    diag = diagnose(manifest, events, load_history(bundle))
    diag["bundle"] = os.path.basename(bundle)
    if as_json:
        print(json.dumps(diag, indent=1, sort_keys=True, default=str))
    elif not quiet:
        _print_diagnosis(bundle, manifest, diag)
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
