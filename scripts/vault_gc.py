#!/usr/bin/env python
"""Size-budgeted GC for a sparse_tpu Vault directory (ISSUE 9 satellite).

The persistent plan-cache tier (``sparse_tpu.vault``,
``SPARSE_TPU_VAULT=<dir>``) grows one verified artifact per distinct
prepared operator; on a long-lived box that is unbounded. The library
sweeps after every write (``vault.gc``, ``SPARSE_TPU_VAULT_CAP_MB``);
this CLI is the operational mirror of ``trim_records.py`` for cron /
round tooling — stdlib-only (no jax import; it must run on boxes where
the serving venv is down), same mtime-LRU policy as the in-library
sweep:

* artifacts (``objects/**/*.stv``) evict oldest-mtime-first until the
  total fits the cap (loads touch mtime, so hot artifacts survive);
* stale tmp files (``tmp/*`` older than 1 h — crashed writers'
  leftovers) are always pruned;
* the quarantine sidecar keeps its newest 32 files (debugging evidence,
  not an archive).

Usage:
    python scripts/vault_gc.py [--dir D] [--cap-mb N] [--dry-run]

``--dir`` defaults to ``$SPARSE_TPU_VAULT``; ``--cap-mb`` to
``$SPARSE_TPU_VAULT_CAP_MB`` (512). Exits 0 always (an absent vault is
"nothing to do", not an error).
"""

from __future__ import annotations

import os
import sys
import time

SUFFIX = ".stv"  # must match sparse_tpu/vault/_store.py
QUARANTINE_KEEP = 32
TMP_MAX_AGE_S = 3600.0


def _files(root: str):
    out = []
    for dirpath, _dirs, names in os.walk(root):
        for name in names:
            p = os.path.join(dirpath, name)
            try:
                st = os.stat(p)
            except OSError:
                continue
            out.append((p, st.st_size, st.st_mtime))
    return out


def gc(vault_dir: str, cap_mb: float, dry_run: bool = False) -> dict:
    """One sweep; returns ``{artifacts, total_mb, evicted, tmp_pruned,
    quarantine_pruned}``."""
    res = {"artifacts": 0, "total_mb": 0.0, "evicted": 0,
           "tmp_pruned": 0, "quarantine_pruned": 0}
    now = time.time()
    # stale tmp files: a crashed writer's leftovers
    for p, _s, mt in _files(os.path.join(vault_dir, "tmp")):
        if now - mt > TMP_MAX_AGE_S:
            if not dry_run:
                try:
                    os.unlink(p)
                except OSError:
                    continue
            res["tmp_pruned"] += 1
    # quarantine sidecar: newest QUARANTINE_KEEP survive
    q = sorted(_files(os.path.join(vault_dir, "quarantine")),
               key=lambda t: t[2])
    for p, _s, _mt in q[:-QUARANTINE_KEEP] if len(q) > QUARANTINE_KEEP else []:
        if not dry_run:
            try:
                os.unlink(p)
            except OSError:
                continue
        res["quarantine_pruned"] += 1
    # artifacts: mtime-LRU down to the cap
    arts = [
        t for t in _files(os.path.join(vault_dir, "objects"))
        if t[0].endswith(SUFFIX)
    ]
    total = sum(s for _p, s, _m in arts)
    res["artifacts"] = len(arts)
    res["total_mb"] = round(total / 2**20, 3)
    for p, s, _mt in sorted(arts, key=lambda t: t[2]):
        if total <= cap_mb * 2**20:
            break
        if not dry_run:
            try:
                os.unlink(p)
            except OSError:
                continue
        total -= s
        res["evicted"] += 1
    return res


def main(argv) -> int:
    vault_dir = os.environ.get("SPARSE_TPU_VAULT", "")
    cap_mb = float(os.environ.get("SPARSE_TPU_VAULT_CAP_MB", "512") or 512)
    dry_run = "--dry-run" in argv
    it = iter(argv)
    for a in it:
        if a == "--dir":
            vault_dir = next(it, "")
        elif a.startswith("--dir="):
            vault_dir = a.split("=", 1)[1]
        elif a == "--cap-mb":
            cap_mb = float(next(it, cap_mb))
        elif a.startswith("--cap-mb="):
            cap_mb = float(a.split("=", 1)[1])
    if not vault_dir:
        print("vault_gc: no vault directory (--dir or SPARSE_TPU_VAULT); "
              "nothing to do")
        return 0
    if not os.path.isdir(vault_dir):
        print(f"vault_gc: {vault_dir} does not exist; nothing to do")
        return 0
    res = gc(vault_dir, cap_mb, dry_run=dry_run)
    mode = " (dry run)" if dry_run else ""
    print(
        f"vault_gc{mode}: {res['artifacts']} artifacts, "
        f"{res['total_mb']} MB vs cap {cap_mb} MB -> "
        f"evicted {res['evicted']}, tmp pruned {res['tmp_pruned']}, "
        f"quarantine pruned {res['quarantine_pruned']}"
    )
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
