"""Flagship benchmark: CG iterations/second on the 2-D 5-point Laplacian.

Mirrors the reference's PDE benchmark (`examples/pde.py -throughput`,
BASELINE.md: 75.9 iters/s on one V100 at 6000^2 unknowns, 300 iterations,
f64). On TPU we run the same problem in f32 (TPU f64 is emulated; the
deviation is documented in SURVEY.md §7) with the matrix generated on device
in the DIA layout and the whole solve compiled into one XLA program.

When the full 6000^2 problem doesn't fit/execute on the available chip the
bench falls back to smaller grids and the baseline comparison is normalized
by row count (same-work throughput), recorded in the metric name.

Fail-soft by design: the measurement runs in a watchdogged SUBPROCESS per
platform attempt (a hung TPU-tunnel backend init cannot take the parent
down), every failure is logged to stderr, and exactly one JSON line is
ALWAYS printed to stdout:
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": N}
"""

import json
import os
import subprocess
import sys
import time
import traceback

BASELINE_ITERS_PER_S = 75.9  # reference: 1x V100, 6000^2, f64 (BASELINE.md)
BASELINE_N = 6000
ITERS = 300


def _sync(out):
    """Force real completion: fetch a scalar from the result.

    jax.block_until_ready is not a reliable fence through remote-tunnel
    platforms (axon), so timing fences on a host fetch of the rho scalar.
    """
    return float(out[-1])


def run_size(n: int, iters: int):
    from sparse_tpu.models.poisson import cg_dia, poisson_cg_state_dia

    state, step = poisson_cg_state_dia(n)
    out = cg_dia(step, *state, iters=iters)  # compile + warm up
    _sync(out)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        out = cg_dia(step, *state, iters=iters)
        _sync(out)
        dt = time.perf_counter() - t0
        best = max(best, iters / dt)
    return best


def worker(platform_arg: str) -> None:
    """Run the measurement on one platform; print the JSON line on success.

    platform_arg: 'default' (whatever the environment provides, e.g. the
    TPU tunnel) or 'cpu' (forced before the jax import).
    """
    if platform_arg == "cpu":
        # the axon plugin overrides the env var; set the config knob too
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    platform = jax.devices()[0].platform
    sizes = [6000, 4000, 2000, 512] if platform != "cpu" else [512]
    for n in sizes:
        try:
            best = run_size(n, ITERS)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print(f"bench worker: size {n} failed; trying next", file=sys.stderr)
            continue
        vs = (best * n * n) / (BASELINE_ITERS_PER_S * BASELINE_N * BASELINE_N)
        print(
            json.dumps(
                {
                    "metric": f"cg_iters_per_s_pde{n}_{platform}",
                    "value": round(best, 2),
                    "unit": "iters/s",
                    "vs_baseline": round(vs, 3),
                }
            )
        )
        sys.stdout.flush()
        return
    sys.exit(3)  # every size failed


def _try_platform(platform_arg: str, timeout_s: int):
    """Run a worker subprocess; return its parsed JSON line or None."""
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", platform_arg],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(
            f"bench: platform {platform_arg!r} timed out after {timeout_s}s",
            file=sys.stderr,
        )
        return None
    sys.stderr.write(proc.stderr[-4000:])
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            if "metric" in rec:
                return rec
        except (json.JSONDecodeError, TypeError):
            continue
    print(
        f"bench: platform {platform_arg!r} exited rc={proc.returncode} "
        "without a metric line",
        file=sys.stderr,
    )
    return None


def main():
    rec = None
    try:
        attempts = [("default", 900)]
        if os.environ.get("JAX_PLATFORMS", "") != "cpu":
            attempts.append(("cpu", 600))
        for platform_arg, timeout_s in attempts:
            rec = _try_platform(platform_arg, timeout_s)
            if rec is not None:
                break
    except Exception:
        traceback.print_exc(file=sys.stderr)
    finally:
        if rec is None:
            rec = {
                "metric": "cg_iters_per_s_pde_none",
                "value": 0.0,
                "unit": "iters/s",
                "vs_baseline": 0.0,
            }
        print(json.dumps(rec))
        sys.stdout.flush()


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    else:
        main()
