"""Flagship benchmark: CG iterations/second on the 2-D 5-point Laplacian.

Mirrors the reference's PDE benchmark (`examples/pde.py -throughput`,
BASELINE.md: 75.9 iters/s on one V100 at 6000^2 unknowns, 300 iterations,
f64). On TPU we run the same problem in f32 (TPU f64 is emulated; the
deviation is documented in SURVEY.md §7) with the matrix generated on device
in the ELL layout and the whole solve compiled into one XLA program.

When the full 6000^2 problem doesn't fit/execute on the available chip the
bench falls back to smaller grids and the baseline comparison is normalized
by row count (same-work throughput), recorded in the metric name.

Prints exactly one JSON line:
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": N}
"""

import json
import time

import jax

BASELINE_ITERS_PER_S = 75.9  # reference: 1x V100, 6000^2, f64 (BASELINE.md)
BASELINE_N = 6000


def _sync(out):
    """Force real completion: fetch a scalar from the result.

    jax.block_until_ready is not a reliable fence through remote-tunnel
    platforms (axon), so timing fences on a host fetch of the rho scalar.
    """
    return float(out[-1])


def run_size(n: int, iters: int):
    from sparse_tpu.models.poisson import cg_dia, poisson_cg_state_dia

    state, step = poisson_cg_state_dia(n)
    out = cg_dia(step, *state, iters=iters)  # compile + warm up
    _sync(out)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        out = cg_dia(step, *state, iters=iters)
        _sync(out)
        dt = time.perf_counter() - t0
        best = max(best, iters / dt)
    return best


def main():
    platform = jax.devices()[0].platform
    sizes = [6000, 4000, 2000] if platform == "tpu" else [512]
    iters = 300
    value, n = None, None
    for n in sizes:
        try:
            value = run_size(n, iters)
            break
        except Exception:
            continue
    if value is None:
        print(
            json.dumps(
                {
                    "metric": f"cg_iters_per_s_pde_{platform}",
                    "value": 0.0,
                    "unit": "iters/s",
                    "vs_baseline": 0.0,
                }
            )
        )
        return
    # Normalize to per-row throughput when not at the baseline size.
    vs = (value * n * n) / (BASELINE_ITERS_PER_S * BASELINE_N * BASELINE_N)
    print(
        json.dumps(
            {
                "metric": f"cg_iters_per_s_pde{n}_{platform}",
                "value": round(value, 2),
                "unit": "iters/s",
                "vs_baseline": round(vs, 3),
            }
        )
    )


if __name__ == "__main__":
    main()
