"""Flagship benchmark: CG iterations/second on the 2-D 5-point Laplacian.

Mirrors the reference's PDE benchmark (`examples/pde.py -throughput`,
BASELINE.md: 75.9 iters/s on one V100 at 6000^2 unknowns, 300 iterations,
f64). On TPU we run the same problem in f32 (TPU f64 is emulated; the
deviation is documented in SURVEY.md §7) with the matrix generated on device
in the DIA layout and the whole solve compiled into one XLA program.

When the full 6000^2 problem doesn't fit/execute on the available chip the
bench falls back to smaller grids and the baseline comparison is normalized
by row count (same-work throughput), recorded in the metric name.

Fail-soft by design: the measurement runs in a watchdogged SUBPROCESS per
platform attempt (a hung TPU-tunnel backend init cannot take the parent
down), every failure is logged to stderr, and at least one JSON line is
ALWAYS printed to stdout — the LAST metric line is authoritative (the
worker checkpoints a record before slow optional sweeps, then prints an
updated one):
  {"metric": ..., "value": N, "unit": "iters/s", "vs_baseline": N}

Budget management (VERDICT r2 #1): a ~120s PROBE subprocess decides
whether the TPU backend is alive BEFORE any full-size attempt can burn
the budget hanging in backend init. On a dead/wedged backend the CPU
fallback line is captured immediately (~minutes into the run, not at the
end), then the probe keeps retrying so a late tunnel recovery still
yields a TPU line within the budget. The TPU worker leads with the
fused-CG headline (the best number) and checkpoints after every stage.
Total budget via BENCH_BUDGET_S (default 870s).
"""

import json
import os
import subprocess
import sys
import time
import traceback

BASELINE_ITERS_PER_S = 75.9  # reference: 1x V100, 6000^2, f64 (BASELINE.md)
BASELINE_N = 6000
ITERS = 300

# -- committed hardware-evidence log (VERDICT r3 #4) ------------------------
# Mirrors the reference's results/summit/*.out verbatim-output convention:
# every successful hardware measurement appends a JSON record (and, for
# example scripts, the verbatim stdout) under results/axon/. When the tunnel
# is wedged at capture time, main() emits the freshest logged TPU record
# clearly labeled {"source": "session-log", "age_s": N} so the round
# artifact carries a hardware-derived number without misrepresenting
# liveness.
HERE = os.path.dirname(os.path.abspath(__file__))
RESULTS_DIR = os.path.join(HERE, "results", "axon")
RECORDS_PATH = os.path.join(RESULTS_DIR, "records.jsonl")


def _log_hw_record(rec: dict) -> None:
    """Append one hardware measurement record to the committed session log."""
    try:
        os.makedirs(RESULTS_DIR, exist_ok=True)
        entry = dict(rec)
        entry["ts"] = time.time()
        entry["iso"] = time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open(RECORDS_PATH, "a") as f:
            f.write(json.dumps(entry) + "\n")
    except Exception:
        traceback.print_exc(file=sys.stderr)


def _log_hw_text(name: str, text: str) -> None:
    """Save an example script's verbatim stdout (the reference's .out style)."""
    try:
        os.makedirs(RESULTS_DIR, exist_ok=True)
        stamp = time.strftime("%Y%m%dT%H%M%SZ", time.gmtime())
        with open(os.path.join(RESULTS_DIR, f"{stamp}_{name}.out"), "w") as f:
            f.write(text)
    except Exception:
        traceback.print_exc(file=sys.stderr)


def _log_session_record(rec, status: str, t_start: float) -> None:
    """Append one machine-parseable SESSION record to records.jsonl on
    EVERY bench run — wedged probe included (the observability gap that
    left earlier rounds without a usable session log when the TPU probe
    timed out). Session records carry ``kind`` and no top-level
    ``metric``, so ``_freshest_session_record`` (which requires a
    ``metric`` with '_tpu') can never mistake one for a live hardware
    measurement. Includes the in-process telemetry summary when
    SPARSE_TPU_TELEMETRY is on (worker subprocesses append their own
    solver/autotune/comm events to the same log directly)."""
    entry = {
        "kind": "bench.session",
        "status": status,
        "budget_spent_s": round(time.monotonic() - t_start, 1),
        "record": rec,
        # watchdog-killed probes this run (ISSUE 6 satellite: the bare
        # 'probe timed out' stderr lines, now a session-record field)
        "timeouts": list(PROBE_TIMEOUTS),
    }
    if os.environ.get("SPARSE_TPU_TELEMETRY"):
        try:
            from sparse_tpu import telemetry

            # the probe timeouts as structured events, emitted here (one
            # deferred batch) so a wedged-tunnel timeout never triggers a
            # first sparse_tpu import mid-run; t_wall preserves when the
            # watchdog actually fired
            for to in PROBE_TIMEOUTS:
                telemetry.record("bench.probe_timeout", **to)
            entry["telemetry"] = telemetry.summary()
        except Exception:
            traceback.print_exc(file=sys.stderr)
    try:
        # plan-cache counters are ALWAYS-ON (plain ints, no telemetry
        # needed): embed them so bench rounds can attribute cache
        # behavior (prepare reuse, batched-bucket compiles) without a
        # separate probe
        from sparse_tpu import plan_cache

        entry["plan_cache"] = plan_cache.stats()
    except Exception:
        traceback.print_exc(file=sys.stderr)
    try:
        # the always-on metrics registry (counters/gauges/histograms —
        # telemetry/_metrics.py): the same numbers metrics_text() would
        # expose to a scrape, embedded so scripts/axon_report.py can
        # roll sessions up without a live process
        from sparse_tpu.telemetry import _metrics

        entry["metrics"] = _metrics.snapshot()
    except Exception:
        traceback.print_exc(file=sys.stderr)
    _log_hw_record(entry)


def _telemetry_models_stage(platform: str) -> None:
    """With telemetry on, bank the session's structural models as events
    (sparse_tpu.telemetry): the samplesort comm model is pure host
    arithmetic, and off-TPU the SpMV comm model and the autotune gate
    decision are recorded too, so even a CPU-only session log documents
    the tile choice and the collective volumes the code WOULD move.
    On TPU only the host-side model runs — real autotune probes and
    solver events come from the measurement stages, and extra eager
    device ops on a fragile tunnel are wedge exposure. Never fatal."""
    try:
        from sparse_tpu import telemetry

        if not telemetry.enabled():
            return
        import numpy as np

        from sparse_tpu.parallel.sort import sort_comm_stats

        keys = np.random.default_rng(0).permutation(1 << 12).astype(np.int64)
        st = sort_comm_stats(keys, 8)
        telemetry.record(
            "comm.sort", S=8, model=True, n=int(keys.size),
            fallback_odd_even=st["fallback_odd_even"],
            bucket_entries_sent_max=st["bucket_entries_sent_max"],
            bytes=8 * (
                st["exchange_bytes_per_shard_max"]
                + st["sample_allgather_bytes_per_shard"]
            ),
        )
        if platform != "tpu":
            import jax.numpy as jnp

            import sparse_tpu
            from sparse_tpu.kernels.dia_spmv import autotune_dia_tile
            from sparse_tpu.parallel.dist import shard_csr

            # records an autotune.result (probed=False, gated) event
            autotune_dia_tile(
                jnp.ones((11, 1 << 14), dtype=jnp.float32),
                tuple(range(-5, 6)), (1 << 14, 1 << 14),
            )
            # shard_csr records the comm.spmv structural model event
            e = np.ones(256)
            A = sparse_tpu.diags([-e[:-1], 2 * e, -e[:-1]], [-1, 0, 1]).tocsr()
            shard_csr(A)
    except Exception:
        traceback.print_exc(file=sys.stderr)


def _freshest_session_record():
    """Newest logged TPU record from records.jsonl, or None."""
    try:
        with open(RECORDS_PATH) as f:
            lines = f.read().splitlines()
    except OSError:
        return None
    best = None
    for line in lines:
        try:
            r = json.loads(line)
        except json.JSONDecodeError:
            continue
        if (
            isinstance(r.get("metric"), str)
            and "_tpu" in r["metric"]
            and isinstance(r.get("ts"), (int, float))
        ):
            if best is None or r["ts"] > best["ts"]:
                best = r
    return best


def _sync(out):
    """Force real completion: fetch a scalar from the result.

    jax.block_until_ready is not a reliable fence through remote-tunnel
    platforms (axon), so timing fences on a host fetch of the rho scalar.
    """
    return float(out[-1])


def run_size(n: int, iters: int):
    from sparse_tpu.models.poisson import cg_dia, poisson_cg_state_dia

    state, step = poisson_cg_state_dia(n)
    out = cg_dia(step, *state, iters=iters)  # compile + warm up
    _sync(out)
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        out = cg_dia(step, *state, iters=iters)
        _sync(out)
        dt = time.perf_counter() - t0
        best = max(best, iters / dt)
    return best


# Approximate HBM bandwidth per device kind for the roofline fraction.
# Labeled approximate: the fraction is a diagnostic, not a spec claim.
_HBM_GBPS = {
    "TPU v2": 700.0,
    "TPU v3": 900.0,
    "TPU v4": 1228.0,
    "TPU v5 lite": 819.0,
    "TPU v5e": 819.0,
    "TPU v5p": 2765.0,
    "TPU v6 lite": 1640.0,
    "TPU v6e": 1640.0,
}


def _time_kernel(step, x, reps=3, slope_k=16):
    """Seconds per application of ``step`` (an [N]->[N] map).

    Host-chained dispatch (v = step(v) repeatedly, data dependence serializes
    on device) with ONE fence per chain; per-op time comes from the slope
    between a 1-op and a (1+slope_k)-op chain, which cancels the fence cost —
    a full round trip through a remote-tunnel backend, easily 100x a fast
    kernel. Each application is scaled by 0.125 so values decay instead of
    overflowing. (A lax.fori_loop would amortize the same way, but
    segment_sum inside fori_loop faults the TPU worker on current libtpu.)
    """
    import jax.numpy as jnp

    scale = jnp.asarray(0.125, x.dtype)

    def chain(k):
        best = float("inf")
        for _ in range(reps):
            t0 = time.perf_counter()
            v = x
            for _ in range(k):
                v = step(v) * scale
            float(v.reshape(-1)[-1])
            best = min(best, time.perf_counter() - t0)
        return best

    v = step(x)
    float(v.reshape(-1)[-1])  # compile + warm
    t1 = chain(1)
    if t1 > 0.5:  # slow kernel: fence cost is noise, one-op chain is enough
        return t1
    tk = chain(1 + slope_k)
    slope = (tk - t1) / slope_k
    if slope <= 0:
        # no measurable slope (overlap/noise ate the chain): report the
        # whole 1-op chain as a conservative bound instead of a bogus ~0
        return t1
    return slope


def kernel_sweep(n: int, platform: str) -> dict:
    """SpMV kernel comparison on the n^2-row 5-point Laplacian.

    Reports GFLOP/s for the segment (general CSR), ELL-gather, DIA (XLA)
    and Pallas paths, plus each path's fraction of the device's approximate
    HBM roofline (VERDICT r1 #6). Pallas variants only run natively on TPU.
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparse_tpu.models.poisson import laplacian_2d_dia, laplacian_2d_ell
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla
    from sparse_tpu.ops.spmv import csr_spmv_ell, csr_spmv_segment

    N = n * n
    ell_idx, ell_val = laplacian_2d_ell(n)
    planes, offsets = laplacian_2d_dia(n)
    x = jnp.ones((N,), dtype=jnp.float32)
    nnz = int(jnp.sum(ell_val != 0))
    flops = 2.0 * nnz

    # bytes per SpMV pass (f32 vals / i32 ids): value+index (or DIA planes)
    # loads + one x load + one y store
    ell_bytes = nnz * 8 + N * 8
    dia_bytes = planes.size * 4 + N * 8

    indptr = jnp.arange(0, N * ell_idx.shape[1] + 1, ell_idx.shape[1], dtype=jnp.int32)
    cols = ell_idx.reshape(-1)
    vals = ell_val.reshape(-1)

    out = {}

    def record(name, seconds, bytes_moved):
        bw_gbps = _HBM_GBPS.get(
            getattr(jax.devices()[0], "device_kind", ""), None
        )
        entry = {"gflops": round(flops / seconds / 1e9, 2)}
        if bw_gbps:
            entry["hbm_frac"] = round(bytes_moved / seconds / (bw_gbps * 1e9), 3)
        out[name] = entry

    def attempt(name, step, bytes_moved):
        try:
            record(name, _time_kernel(step, x), bytes_moved)
        except Exception as e:  # one kernel failing must not hide the rest
            out[name] = {"error": str(e)[:200]}
            traceback.print_exc(file=sys.stderr)

    attempt("segment", lambda xx: csr_spmv_segment(indptr, cols, vals, xx, N), ell_bytes)
    attempt("ell_xla", lambda xx: csr_spmv_ell(ell_idx, ell_val, xx), ell_bytes)
    attempt("dia_xla", lambda xx: dia_spmv_xla(planes, offsets, xx, (N, N)), dia_bytes)

    # prepared SELL-C-sigma rows (the general-matrix prepare/execute path).
    # Bytes: stored slots (value+index) + x + y — the pack's actual traffic.
    try:
        from sparse_tpu.kernels.sell_spmv import PreparedCSR

        sprep = PreparedCSR(indptr, cols, vals, (N, N))
        sell_bytes = sprep.plan.stored_slots * 8 + N * 8
        attempt("sell_xla", sprep.matvec_xla, sell_bytes)
        if platform == "tpu":
            # the Pallas row-block kernel; a Mosaic lowering failure fails
            # over to XLA once — label the row the way the old ell_pallas
            # delegating row was labeled, "(->xla)", so the sweep never
            # claims a kernel that didn't run
            attempt("sell_pallas", sprep, sell_bytes)
            from sparse_tpu.resilience import failover as _failover

            if _failover.failed(sprep.KERNEL, sprep) and "sell_pallas" in out:
                out["sell_pallas(->xla)"] = out.pop("sell_pallas")
        else:
            # off-TPU the kernel only exists in interpret mode (pure
            # debugging; timing it would be meaningless) — its measured
            # path here IS sell_xla above
            out["sell_pallas"] = {"note": "interpret-only off-TPU; measured path is sell_xla"}
    except Exception as e:
        out["sell_xla"] = {"error": str(e)[:200]}
        traceback.print_exc(file=sys.stderr)

    if platform == "tpu":
        from sparse_tpu.kernels.dia_spmv import PreparedDia, dia_spmv_pallas

        attempt(
            "dia_pallas",
            lambda xx: dia_spmv_pallas(planes, offsets, xx, (N, N)),
            dia_bytes,
        )
        # packed prepared layout: planes resident, per-call cost is the
        # kernel plus x pad / y trim (the honest drop-in form)
        prep = PreparedDia(planes, offsets, (N, N))
        attempt("dia_pallas_packed", prep, dia_bytes)
        # no ell_pallas row: general (non-banded) gather SpMV has no
        # Mosaic-lowering-compatible kernel yet; its measured path IS
        # ell_xla above (the dead delegating kernel was removed, r3)
    return out


def skewed_degree_csr(m: int, seed: int = 7):
    """Power-law-degree SPD test matrix (scipy CSR, f32): pareto row degrees
    capped at m/20, symmetrized, diagonally dominant — the row-length-skew
    shape where ELL's global-max padding explodes and the segment path was
    the only general option before the SELL packing."""
    import numpy as np
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    deg = np.minimum((rng.pareto(1.2, m) * 4 + 1).astype(int), max(m // 20, 8))
    rows = np.repeat(np.arange(m), deg)
    cols = rng.integers(0, m, rows.shape[0])
    vals = rng.random(rows.shape[0])
    G = sp.coo_matrix((vals, (rows, cols)), shape=(m, m)).tocsr()
    A = (G + G.T) * 0.5
    A = A + sp.diags(np.asarray(np.abs(A).sum(axis=1)).ravel() + 1.0)
    return A.tocsr().astype(np.float32)


def run_skewed_cg(m: int = 20000, iters: int = 100) -> dict:
    """Skewed-degree CSR CG row: prepared-SELL vs segment-mode iters/s.

    The tracked number for the general-matrix prepare/execute split
    (ISSUE 2): both modes run the same compiled CG device loop; the only
    difference is the SpMV kernel the trace embeds. Also reports the
    plan-cache hit rate over a host-driven ``iters``-iteration solve
    (per-iteration eager matvecs: 1 miss at prepare, hits thereafter).
    """
    import numpy as np

    import sparse_tpu
    from sparse_tpu import linalg, plan_cache
    from sparse_tpu.config import settings

    A_s = skewed_degree_csr(m)
    b = np.random.default_rng(3).standard_normal(m).astype(np.float32)
    out = {"m": m, "nnz": int(A_s.nnz), "iters": iters,
           "max_deg": int(np.diff(A_s.indptr).max()),
           "mean_deg": round(A_s.nnz / m, 1)}
    prev = settings.spmv_mode
    try:
        for mode in ("segment", "sell"):
            settings.spmv_mode = mode
            A = sparse_tpu.csr_array(A_s)
            x, _ = linalg.cg(A, b, maxiter=iters, tol=1e-30, conv_test_iters=2 * iters)
            np.asarray(x)  # warm + fence
            best = 0.0
            for _ in range(3):
                t0 = time.perf_counter()
                x, it = linalg.cg(A, b, maxiter=iters, tol=1e-30,
                                  conv_test_iters=2 * iters)
                np.asarray(x)
                best = max(best, it / (time.perf_counter() - t0))
            out[f"{mode}_iters_per_s"] = round(best, 1)
        if out.get("segment_iters_per_s"):
            out["sell_vs_segment"] = round(
                out["sell_iters_per_s"] / out["segment_iters_per_s"], 2
            )
        # plan-cache hit rate over a host-loop solve (per-iteration eager
        # matvecs — the acceptance instrument: 1 miss at prepare, hits
        # thereafter). A no-op callback forces the host loop.
        settings.spmv_mode = "sell"
        A = sparse_tpu.csr_array(A_s)
        plan_cache.reset_stats()
        linalg.cg(A, b, maxiter=iters, tol=1e-30, conv_test_iters=2 * iters,
                  callback=lambda _x: None)
        st = plan_cache.stats()
        out["plan_cache"] = {
            "hits": st["hits"], "misses": st["misses"],
            "hit_rate": round(st["hit_rate"], 4),
        }
    finally:
        settings.spmv_mode = prev
    return out


def run_batched_cg(B: int = 32, n: int = 4096, iters: int = 60) -> dict:
    """Batched-solve row (ISSUE 3): B same-pattern SPD systems through the
    batch subsystem vs B sequential ``linalg.cg`` calls — the serving
    shape (same mesh, different coefficients/rhs). The tracked numbers:

    * ``speedup``: sequential wall time / first batched dispatch (compile
      included on BOTH sides — the honest cold-traffic comparison), with
      ``speedup_warm`` for the steady state. Acceptance: >= 4x on CPU.
    * ``plan_cache``: exactly ONE miss for the batch's single bucket
      (asserted via the always-on cache stats; the pattern pack is warmed
      outside the window, every later dispatch hits).
    * ``b1_match``: batch-of-1 numerically matches the unbatched solve.

    Fixed work (tol below reach, conv test at the end) so both sides run
    ``iters`` CG iterations per system.
    """
    import numpy as np
    import scipy.sparse as sp

    import sparse_tpu
    from sparse_tpu import linalg, plan_cache
    from sparse_tpu.batch import BatchedCSR, SolveSession

    rng = np.random.default_rng(11)
    e = np.ones(n, dtype=np.float32)
    base = sp.diags(
        [-e[:-1], 2.5 * e, -e[:-1]], [-1, 0, 1], format="csr"
    ).astype(np.float32)
    base.sort_indices()
    # same pattern, per-lane coefficients: scaled diagonal keeps SPD
    mats = []
    for i in range(B):
        Ai = base.copy()
        Ai.setdiag(2.5 + rng.random(n).astype(np.float32))
        Ai.sort_indices()
        mats.append(Ai.tocsr())
    rhs = rng.standard_normal((B, n)).astype(np.float32)
    cti = 2 * iters  # conv test only at iters-1: fixed work both sides
    out = {"B": B, "n": n, "iters": iters}

    # -- sequential lane: B independent cg() calls (each traces its own
    # compiled loop — the per-request cost a serving stack actually pays)
    t0 = time.perf_counter()
    seq = []
    for i in range(B):
        x, it = linalg.cg(
            sparse_tpu.csr_array(mats[i]), rhs[i], tol=1e-30,
            maxiter=iters, conv_test_iters=cti,
        )
        seq.append(np.asarray(x))
        assert it == iters
    t_seq = time.perf_counter() - t0
    out["seq_s"] = round(t_seq, 3)
    out["seq_solves_per_s"] = round(B / t_seq, 2)

    # -- batched lane: one SolveSession dispatch per flush
    ses = SolveSession("cg", batch_max=B, conv_test_iters=cti)
    pattern = ses.pattern_of(mats[0])
    pattern.sell_pack()  # warm the pattern pack outside the window
    snap = plan_cache.snapshot()
    t0 = time.perf_counter()
    X, its, _r2 = ses.solve_many(mats, rhs, tol=1e-30, maxiter=iters)
    t_first = time.perf_counter() - t0
    d = plan_cache.delta(snap)
    out["batched_first_s"] = round(t_first, 3)
    out["speedup"] = round(t_seq / t_first, 2)
    # exactly one plan-cache miss per bucket (1 bucket here): the bucket
    # program; the pattern pack HITS from inside its build
    out["plan_cache"] = {"buckets": 1, **d,
                         "one_miss_per_bucket": d["misses"] == 1}
    snap = plan_cache.snapshot()
    t0 = time.perf_counter()
    ses.solve_many(mats, rhs, tol=1e-30, maxiter=iters)
    t_warm = time.perf_counter() - t0
    d2 = plan_cache.delta(snap)
    out["batched_warm_s"] = round(t_warm, 3)
    out["speedup_warm"] = round(t_seq / t_warm, 2)
    out["warm_dispatch_cache"] = d2  # expect 0 misses: program reused
    out["batched_solves_per_s"] = round(B / t_warm, 2)
    # per-lane results match the sequential solves
    out["lanes_match"] = bool(
        max(
            float(np.max(np.abs(X[i] - seq[i]))) for i in range(B)
        ) < 1e-3
    )

    # -- batch-of-1 parity: the batched path degenerates exactly
    x1, info = linalg.batched_cg(
        BatchedCSR(pattern, mats[0].data[None, :]), rhs[:1], tol=1e-30,
        maxiter=iters, conv_test_iters=cti,
    )
    diff = float(np.max(np.abs(np.asarray(x1)[0] - seq[0])))
    out["b1_match"] = diff < 1e-4
    out["b1_max_abs_diff"] = diff
    out["b1_iters"] = int(np.asarray(info.iters)[0])
    return out


def run_cold_start(B: int = 8, n: int = 2048, iters: int = 40) -> dict:
    """Cold-start row (ISSUE 9): the restart tax of the ``batched_cg``
    serving shape, measured at the three cache temperatures the Vault
    story distinguishes:

    * ``cold_s``: fresh process equivalent — empty vault, cleared
      in-process plan cache; the first ``solve_many`` pays pattern pack
      + bucket-program trace/compile.
    * ``disk_warm_s``: killed-and-restarted process equivalent — the
      in-process tier cleared again, but the vault retained; the session
      replays the warm-start manifest at construction (``replay_s``) so
      the timed serving call runs at ZERO plan-cache misses
      (``disk_warm_misses`` pins it).
    * ``warm_s``: steady state (same session again).

    The tracked win is ``disk_warm_s`` ≈ ``warm_s`` << ``cold_s``; the
    row embeds in the bench session record, and ``scripts/axon_report.py``
    lifts ``cold_start.{cold_s,disk_warm_s,warm_s}`` onto the
    ``--compare`` regression surface.
    """
    import shutil
    import tempfile

    import numpy as np
    import scipy.sparse as sp

    from sparse_tpu import plan_cache, vault
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings

    rng = np.random.default_rng(17)
    e = np.ones(n, dtype=np.float32)
    base = sp.diags(
        [-e[:-1], 2.5 * e, -e[:-1]], [-1, 0, 1], format="csr"
    ).astype(np.float32)
    mats = []
    for _ in range(B):
        Ai = base.copy()
        Ai.setdiag(2.5 + rng.random(n).astype(np.float32))
        Ai.sort_indices()
        mats.append(Ai.tocsr())
    rhs = rng.standard_normal((B, n)).astype(np.float32)
    cti = 2 * iters  # fixed work: conv test only at the end
    out = {"B": B, "n": n, "iters": iters}
    vdir = tempfile.mkdtemp(prefix="stpu_bench_vault_")
    old_vault = settings.vault
    try:
        settings.vault = vdir

        def serve(ses):
            snap = plan_cache.snapshot()
            t0 = time.perf_counter()
            ses.solve_many(mats, rhs, tol=1e-30, maxiter=iters)
            return time.perf_counter() - t0, plan_cache.delta(snap)

        # cold: both tiers empty
        plan_cache.clear()
        ses = SolveSession("cg", batch_max=B, conv_test_iters=cti,
                           warm_start=False)
        out["cold_s"], d_cold = serve(ses)
        out["cold_misses"] = d_cold["misses"]
        # disk-warm: in-process tier gone (the restart), vault retained
        plan_cache.clear()
        t0 = time.perf_counter()
        # synchronous replay on purpose: this row MEASURES the replay
        # itself (replay_s); the async warm path is chaos scenario 10's
        # drill and the pipeline columns of sustained_cg
        ses2 = SolveSession("cg", batch_max=B, conv_test_iters=cti,
                            warm_start=True, warm_async=False)
        out["replay_s"] = time.perf_counter() - t0
        out["replayed_programs"] = ses2.warm_replayed
        out["disk_warm_s"], d_dw = serve(ses2)
        out["disk_warm_misses"] = d_dw["misses"]  # acceptance: 0
        out["disk_warm_zero_miss"] = d_dw["misses"] == 0
        # warm: steady state of the same process
        out["warm_s"], _ = serve(ses2)
        out["cold_vs_disk_warm"] = round(
            out["cold_s"] / max(out["disk_warm_s"], 1e-9), 2
        )
        vs = vault.stats()
        out["vault"] = {
            k: vs[k] for k in ("hits", "misses", "writes", "quarantined")
        }
        for k in ("cold_s", "disk_warm_s", "warm_s", "replay_s"):
            out[k] = round(out[k], 4)
    finally:
        settings.vault = old_vault
        shutil.rmtree(vdir, ignore_errors=True)
    return out


def _pde2d_varcoef(g: int, seed: int, sigma: float = 3.0,
                   dtype=None):
    """Ill-conditioned 2-D PDE profile: variable-coefficient 5-point
    Laplacian with a lognormal coefficient field (contrast ~ e^{4 sigma},
    i.e. >1e5 at the default sigma) plus a small zero-order shift — SPD,
    shared sparsity pattern for every seed, and brutally slow for
    unpreconditioned CG (the diagonal varies over orders of magnitude,
    which is exactly what Jacobi-family preconditioners fix)."""
    import numpy as np
    import scipy.sparse as sp

    rng = np.random.default_rng(seed)
    k = np.exp(rng.normal(0.0, sigma, size=(g, g)))
    wh = 0.5 * (k[:, :-1] + k[:, 1:])
    wv = 0.5 * (k[:-1, :] + k[1:, :])
    N = g * g
    idx = np.arange(N).reshape(g, g)
    rows = np.concatenate([
        idx[:, :-1].ravel(), idx[:, 1:].ravel(),
        idx[:-1, :].ravel(), idx[1:, :].ravel(),
    ])
    cols = np.concatenate([
        idx[:, 1:].ravel(), idx[:, :-1].ravel(),
        idx[1:, :].ravel(), idx[:-1, :].ravel(),
    ])
    vals = np.concatenate([wh.ravel(), wh.ravel(), wv.ravel(), wv.ravel()])
    off = sp.coo_matrix((-vals, (rows, cols)), shape=(N, N)).tocsr()
    diag = -np.asarray(off.sum(axis=1)).ravel() + 1e-4
    A = (off + sp.diags(diag)).tocsr()
    if dtype is not None:
        A = A.astype(dtype)
    A.sort_indices()
    return A


def run_precond_cg(B: int = 16, g: int = 32, tol: float = 1e-6,
                   kinds=("bjacobi", "jacobi")) -> dict:
    """Preconditioned batched-solve row (ISSUE 14): end-to-end batched
    solve TIME — not iters/s — on the ill-conditioned 2-D PDE profile,
    preconditioned vs not, at MATCHING residual tolerance. The win
    condition (ROADMAP item 3): >= 2x end-to-end with bjacobi or ilu0.

    Tracked numbers:

    * ``none.end_to_end_s`` / ``<kind>.end_to_end_s``: warm steady-state
      wall per flush of the same B-lane stack (programs compiled outside
      the window — this row measures ITERATIONS saved, not compile tax).
    * ``speedup``: none / best preconditioned; acceptance >= 2x.
    * ``symbolic_per_bucket``: exactly ONE pattern-level preconditioner
      build per (pattern, bucket) across repeated flushes, from the
      always-on ``precond.builds`` counter + plan-cache stats.
    * ``warm_restart``: a fresh session over the retained vault replays
      the precond-KEYED manifest entry and serves at zero plan-cache
      misses (``disk_warm_zero_miss`` analog for preconditioned
      programs).
    """
    import shutil
    import tempfile

    import numpy as np

    from sparse_tpu import plan_cache
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings
    from sparse_tpu.telemetry import _metrics

    n = g * g
    rng = np.random.default_rng(29)
    mats = [_pde2d_varcoef(g, seed=100 + i) for i in range(B)]
    rhs = rng.standard_normal((B, n))
    maxiter = 60 * n
    out = {"B": B, "n": n, "profile": f"varcoef_pde{g}x{g}_f64",
           "tol": tol}

    def builds_count(kind):
        return int(_metrics.counter("precond.builds", kind=kind).value)

    vdir = tempfile.mkdtemp(prefix="stpu_bench_precond_")
    old_vault = settings.vault
    try:
        settings.vault = vdir
        plan_cache.clear()

        def timed(ses):
            t0 = time.perf_counter()
            X, its, r2 = ses.solve_many(mats, rhs, tol=tol,
                                        maxiter=maxiter)
            dt = time.perf_counter() - t0
            ok = bool((np.sqrt(r2) <= tol * 1.01).all())
            return dt, float(its.mean()), ok, X

        # unpreconditioned reference (same session knobs, key has no
        # .M suffix — the historic program)
        ses0 = SolveSession("cg", batch_max=B, warm_start=False,
                            requeue=False)
        timed(ses0)  # compile outside the window
        t_none, it_none, ok_none, X0 = timed(ses0)
        out["none"] = {"end_to_end_s": round(t_none, 4),
                       "iters_mean": round(it_none, 1),
                       "converged": ok_none}

        best_kind, best_t = None, None
        for kind in kinds:
            b0 = builds_count(kind)
            ses = SolveSession("cg", batch_max=B, warm_start=False,
                               requeue=False, precond=kind)
            t_build0 = float(
                _metrics.counter("precond.build_seconds").value
            )
            timed(ses)  # compile + symbolic build outside the window
            build_s = float(
                _metrics.counter("precond.build_seconds").value
            ) - t_build0
            snap = plan_cache.snapshot()
            t_k, it_k, ok_k, Xk = timed(ses)
            d = plan_cache.delta(snap)
            row = {
                "end_to_end_s": round(t_k, 4),
                "iters_mean": round(it_k, 1),
                "converged": ok_k,
                "build_s": round(build_s, 4),
                # warm flush: zero misses AND zero fresh symbolic
                # builds — one factorization per (pattern, bucket), ever
                "warm_misses": d["misses"],
                "symbolic_builds": builds_count(kind) - b0,
                "symbolic_per_bucket": (
                    d["misses"] == 0 and builds_count(kind) - b0 <= 1
                ),
                # matching-tolerance honesty: same solution either way
                "match": bool(np.abs(Xk - X0).max() < 50 * tol),
            }
            out[kind] = row
            if ok_k and (best_t is None or t_k < best_t):
                best_kind, best_t = kind, t_k
        if best_kind is not None:
            out["best_kind"] = best_kind
            out["end_to_end_s"] = out[best_kind]["end_to_end_s"]
            out["iters_mean"] = out[best_kind]["iters_mean"]
            out["build_s"] = out[best_kind]["build_s"]
            out["speedup"] = round(t_none / max(best_t, 1e-9), 2)
            out["win_2x"] = bool(out["speedup"] >= 2.0)

            # precond-keyed warm restart through the vault manifest:
            # the in-process tier cleared (the restart), the vault
            # retained — the fresh session replays the .M-keyed program
            # and serves at zero plan-cache misses
            plan_cache.clear()
            ses_w = SolveSession("cg", batch_max=B, warm_start=True,
                                 warm_async=False, requeue=False,
                                 precond=best_kind)
            snap = plan_cache.snapshot()
            t_w, _it, ok_w, _X = timed(ses_w)
            d_w = plan_cache.delta(snap)
            out["warm_restart"] = {
                "replayed": ses_w.warm_replayed,
                "serving_misses": d_w["misses"],
                "zero_miss": d_w["misses"] == 0,
                "end_to_end_s": round(t_w, 4),
                "converged": ok_w,
            }
    finally:
        settings.vault = old_vault
        shutil.rmtree(vdir, ignore_errors=True)
    return out


def run_mixed_cg(B: int = 16, g: int = 512, tol_rel: float = 1e-3) -> dict:
    """Mixed-precision row (ISSUE 15): end-to-end batched solve time on
    the pde512 banded profile — exact f64 vs f32+IR vs bf16-storage IR
    at MATCHING achieved relative residual, plus the values-bytes-moved
    column. The win condition (acceptance): >= 1.5x end-to-end for
    f32+IR over exact f64 on the CPU lane.

    Tracked numbers:

    * ``exact_s`` / ``f32ir_s`` / ``bf16ir_s``: wall per warm batched
      solve of the same B-lane stack (programs compiled outside the
      window via a 3-iteration warm-up call of the SAME jitted program
      — ``maxiter`` is a traced argument).
    * ``speedup`` (exact/f32ir; acceptance >= 1.5) and
      ``speedup_bf16`` (exact/bf16ir).
    * ``values_bytes_per_iter``: value-plane bytes streamed per inner
      iteration across the batch — ``D * N * itemsize * B``; the
      ``bytes_ratio_*`` columns pin the 2x (f32) / 4x (bf16) storage
      reduction vs f64.
    * matching-tolerance honesty: every variant's achieved max relative
      residual is recorded and must be <= ``tol_rel``; the IR outer
      loop verifies in f64, so reduced storage never relaxes the
      contract.

    All variants share the masked batched loop cores and the DIA
    matvec (``ops.dia_spmv.dia_spmv_xla``; ``acc_dtype=f32`` widens the
    bf16 planes at the multiply) — the same formulation the pde512
    headline rides, so the delta is precision, not kernel choice.
    """
    import numpy as np

    from sparse_tpu import mixed
    from sparse_tpu.batch import krylov
    from sparse_tpu.config import settings
    from sparse_tpu.models.poisson import poisson_cg_state_dia
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    import jax
    import jax.numpy as jnp

    N = g * g
    offsets = (-g, -1, 0, 1, g)
    state64, _ = poisson_cg_state_dia(g, dtype=jnp.float64)
    planes64 = state64[0]
    rng = np.random.default_rng(41)
    rhs = jnp.asarray(rng.standard_normal((B, N)))
    tols = jnp.full((B,), tol_rel) * jnp.linalg.norm(rhs, axis=-1)
    inner = settings.ir_inner or min(N, 4000)
    outer_cap = settings.ir_outer

    def mk(planes, acc=None):
        def mv(X):
            return jax.vmap(
                lambda v: dia_spmv_xla(planes, offsets, v, (N, N),
                                       acc_dtype=acc)
            )(X)

        return mv

    mv64 = mk(planes64)
    variants = {
        "exact": jax.jit(
            lambda rhs, tols, mi: krylov._cg_loop(
                mv64, rhs, jnp.zeros_like(rhs), tols, mi, 25
            )
        ),
    }
    for policy, planes, acc in (
        ("f32ir", planes64.astype(jnp.float32), None),
        ("bf16ir", planes64.astype(jnp.bfloat16), jnp.float32),
    ):
        mvl = mk(planes, acc)
        variants[policy] = jax.jit(
            lambda rhs, tols, mi, mvl=mvl, policy=policy: mixed.ir_loop(
                mv64, mvl, rhs, jnp.zeros_like(rhs), tols, mi, 25,
                inner, outer_cap, mixed.default_eta(policy), jnp.float32,
            )
        )

    itemsize = {"exact": 8, "f32ir": 4, "bf16ir": 2}
    out = {"B": B, "n": N, "profile": f"pde{g}_dia_f64", "tol_rel": tol_rel,
           "inner_iters": inner}
    rhs_h = np.asarray(rhs)
    rnorms = np.linalg.norm(rhs_h, axis=-1)
    for tag, fn in variants.items():
        jax.block_until_ready(fn(rhs, tols, 3))  # compile outside the window
        t0 = time.perf_counter()
        res = fn(rhs, tols, 40 * N)
        jax.block_until_ready(res)
        dt = time.perf_counter() - t0
        X, iters = res[0], res[1]
        conv = np.asarray(res[3])
        R = np.asarray(mv64(X)) - rhs_h
        rel = float((np.linalg.norm(R, axis=-1) / rnorms).max())
        row = {
            "end_to_end_s": round(dt, 3),
            "iters_mean": round(float(np.asarray(iters).mean()), 1),
            "achieved_rel_resid": rel,
            "converged": bool(conv.all()) and rel <= tol_rel * 1.01,
            "values_bytes_per_iter": len(offsets) * N * itemsize[tag] * B,
        }
        if tag != "exact":
            row["ir_outer"] = int(np.asarray(res[4]))
        out[tag] = row
        out[f"{tag}_s"] = row["end_to_end_s"]
    e, f, bf = out["exact"], out["f32ir"], out["bf16ir"]
    if f["converged"]:
        out["speedup"] = round(
            e["end_to_end_s"] / max(f["end_to_end_s"], 1e-9), 2
        )
        out["win_1_5x"] = bool(out["speedup"] >= 1.5)
    if bf["converged"]:
        out["speedup_bf16"] = round(
            e["end_to_end_s"] / max(bf["end_to_end_s"], 1e-9), 2
        )
    out["bytes_ratio_f32"] = round(
        e["values_bytes_per_iter"] / f["values_bytes_per_iter"], 2
    )
    out["bytes_ratio_bf16"] = round(
        e["values_bytes_per_iter"] / bf["values_bytes_per_iter"], 2
    )
    return out


def run_auto_cg(B: int = 8, tol: float = 1e-6) -> dict:
    """Autopilot policy-tuning row (ISSUE 16): the online tuner vs every
    hand-picked static policy, per profile, at MATCHING tolerance.

    Three profiles with different best arms — the case for closing the
    telemetry->configuration loop is exactly that no single static
    config wins everywhere:

    * ``pde_well``: low-contrast variable-coefficient PDE (sigma=0.5) —
      preconditioning is mostly overhead here;
    * ``pde_ill``: high-contrast PDE (sigma=3) — Jacobi-family precond
      is a large win, compounded by reduced-precision inner loops;
    * ``skewed_general``: power-law-degree SPD general matrix.

    Tracked numbers, per profile:

    * ``static.<arm>.end_to_end_s``: warm steady-state wall per flush
      for each candidate pinned statically (the tuner's whole grid,
      including the ``precond_dtype=storage`` compounding arm);
    * ``auto.end_to_end_s`` / ``auto.arm``: the converged tuner's
      pinned steady state and which arm it chose;
    * ``regret``: auto / best-static wall ratio (1.0 == perfect pick);
      ``auto_matches_best`` allows a 20% wall-noise band OR an exact
      arm match — acceptance is "auto >= best static per profile";
    * ``beats_global_static``: auto strictly under the SINGLE global
      default (the unpreconditioned exact arm) — must hold on >= 1
      profile.

    Plus a ``restart`` drill: a fresh tuner over the retained vault
    restores the pde_ill decision and serves tuned from the FIRST
    request (zero trials spent re-exploring).
    """
    import shutil
    import tempfile

    import numpy as np

    from sparse_tpu import autopilot, plan_cache
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings

    def lanes_from(A, B):
        # B lanes sharing one sparsity pattern: per-lane diagonal scale
        # (keeps SPD, keeps the fingerprint — the batch path's contract)
        d = A.diagonal()
        mats = []
        for i in range(B):
            Ai = A.copy()
            Ai.setdiag(d * (1.0 + 0.15 * i / max(B - 1, 1)))
            Ai.sort_indices()
            mats.append(Ai)
        return mats

    # distinct grid sizes: the tuner keys groups by PATTERN fingerprint,
    # so same-size well/ill profiles would share one group (the second
    # would restore the first's decision instead of tuning its own)
    profiles = {
        "pde_well": [_pde2d_varcoef(24, seed=200 + i, sigma=0.5)
                     for i in range(B)],
        "pde_ill": [_pde2d_varcoef(32, seed=300 + i, sigma=3.0)
                    for i in range(B)],
        "skewed_general": lanes_from(
            skewed_degree_csr(1500).astype(np.float64), B),
    }
    rng = np.random.default_rng(53)
    out = {"B": B, "tol": tol,
           "grid": [autopilot.arm_id(s) for s in autopilot.DEFAULT_GRID]}
    vdir = tempfile.mkdtemp(prefix="stpu_bench_auto_")
    old_vault = settings.vault
    try:
        settings.vault = vdir
        restart_args = None
        for name, mats in profiles.items():
            n = mats[0].shape[0]
            rhs = rng.standard_normal((B, n))
            maxiter = 60 * n
            row = {"n": n}

            def timed(ses, reps=3, mats=mats, rhs=rhs, maxiter=maxiter):
                best = its = ok = None
                for _ in range(reps):
                    t0 = time.perf_counter()
                    _X, it, r2 = ses.solve_many(mats, rhs, tol=tol,
                                                maxiter=maxiter)
                    dt = time.perf_counter() - t0
                    if best is None or dt < best:
                        best = dt
                        its = float(it.mean())
                        ok = bool((np.sqrt(r2) <= tol * 1.5).all())
                return best, its, ok

            statics = {}
            for spec in autopilot.DEFAULT_GRID:
                arm = autopilot.arm_id(spec)
                plan_cache.clear()
                ses = SolveSession("cg", batch_max=B, warm_start=False,
                                   precond=spec.get("precond"),
                                   dtype_policy=spec.get("dtype_policy"),
                                   precond_dtype=spec.get("precond_dtype"))
                timed(ses, reps=1)  # compile outside the window
                t_s, it_s, ok_s = timed(ses)
                statics[arm] = {"end_to_end_s": round(t_s, 4),
                                "iters_mean": round(it_s, 1),
                                "converged": ok_s}
            conv = {a: r for a, r in statics.items() if r["converged"]}
            best_arm = min(conv, key=lambda a: conv[a]["end_to_end_s"])
            row["static"] = statics
            row["best_static"] = best_arm
            row["best_static_s"] = statics[best_arm]["end_to_end_s"]

            # the tuner under full exploration pressure (epsilon=1, one
            # trial per arm per halving round): converge, then measure
            # the PINNED steady state on the same warm programs
            plan_cache.clear()
            ap = autopilot.Autopilot(grid=autopilot.DEFAULT_GRID,
                                     epsilon=1.0, trials=1)
            ses = SolveSession("cg", batch_max=B, warm_start=False,
                               autopilot=ap)
            flushes = 0
            gr = {}
            for _ in range(60):
                ses.solve_many(mats, rhs, tol=tol, maxiter=maxiter)
                flushes += 1
                groups = list(ses.session_stats().get(
                    "autopilot", {}).get("groups", {}).values())
                if groups and all(x["phase"] == "converged"
                                  for x in groups):
                    gr = groups[0]
                    break
            t_a, it_a, ok_a = timed(ses)
            row["auto"] = {"end_to_end_s": round(t_a, 4),
                           "iters_mean": round(it_a, 1),
                           "converged": ok_a,
                           "arm": gr.get("arm"),
                           "phase": gr.get("phase", "exploring"),
                           "trials": gr.get("trials"),
                           "tuning_flushes": flushes}
            row["regret"] = round(t_a / max(row["best_static_s"], 1e-9), 3)
            row["auto_matches_best"] = bool(
                gr.get("arm") == best_arm or row["regret"] <= 1.2)
            row["beats_global_static"] = bool(
                ok_a and statics["static"]["converged"]
                and t_a < statics["static"]["end_to_end_s"])
            out[name] = row
            if name == "pde_ill":
                restart_args = (mats, rhs, maxiter)

        out["auto_matches_best_all"] = all(
            out[p]["auto_matches_best"] for p in profiles)
        out["beats_global_static_any"] = any(
            out[p]["beats_global_static"] for p in profiles)
        out["win"] = bool(out["auto_matches_best_all"]
                          and out["beats_global_static_any"])
        # trend/report scalars: worst pick quality across profiles and
        # the headline auto-vs-global-default win on the ill profile
        out["regret_worst"] = max(out[p]["regret"] for p in profiles)
        ill = out["pde_ill"]
        if ill["static"]["static"]["converged"]:
            out["ill_speedup_vs_global"] = round(
                ill["static"]["static"]["end_to_end_s"]
                / max(ill["auto"]["end_to_end_s"], 1e-9), 2)

        # restart drill: fresh process (in-process tier cleared, vault
        # retained, NEW tuner) — tuned from the first request
        if restart_args is not None:
            mats, rhs, maxiter = restart_args
            plan_cache.clear()
            ap2 = autopilot.Autopilot(grid=autopilot.DEFAULT_GRID)
            ses2 = SolveSession("cg", batch_max=B, warm_start=True,
                                warm_async=False, autopilot=ap2)
            ses2.solve_many(mats, rhs, tol=tol, maxiter=maxiter)
            groups = list(ses2.session_stats().get(
                "autopilot", {}).get("groups", {}).values())
            g2 = groups[0] if groups else {}
            out["restart"] = {
                "restored": bool(g2.get("restored")),
                "arm": g2.get("arm"),
                "replayed": ses2.warm_replayed,
                "tuned_from_first_request": bool(
                    g2.get("restored") and g2.get("phase") == "converged"),
            }
    finally:
        settings.vault = old_vault
        shutil.rmtree(vdir, ignore_errors=True)
    return out


def run_ingest(n: int = 20000, row_nnz: int = 8, seed: int = 31) -> dict:
    """Ingest data-plane row (ISSUE 18): rows/s through the sharded
    samplesort COO->CSR, and through FULL cold onboarding (sort ->
    pattern -> SELL pack -> bucket prebuild -> first solve) vs the
    dedup-hit path of a structural re-arrival.

    Tracked numbers:

    * ``sort.rows_per_s`` / ``sort.entries_per_s``: the distributed
      samplesort alone (second run, setup warm);
    * ``cold.onboard_ms`` / ``cold.rows_per_s``: submit -> ticket-ready
      wall for an unseen pattern (the whole data plane, compiles
      included), plus its first-solve latency and the plan-cache misses
      the onboarding spent;
    * ``dedup.onboard_ms`` / ``dedup.rows_per_s``: the same structure
      re-arriving with new values — fingerprint hit, values grafted,
      ZERO new plan-cache misses (``dedup.plan_misses`` is the
      acceptance number), plus the first-solve latency on the grafted
      CSR;
    * ``dedup.speedup``: cold/dedup onboarding wall ratio;
    * ``win``: dedup onboarded faster than cold AND spent zero misses.
    """
    import time as _time

    import numpy as np

    from sparse_tpu import plan_cache
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.ingest import ingest_coo_to_csr
    from sparse_tpu.parallel.mesh import get_mesh

    rng = np.random.default_rng(seed)
    k = n * row_nnz
    r = rng.integers(0, n, size=k)
    c = rng.integers(0, n, size=k)
    v = 0.05 * rng.standard_normal(k)
    d = np.arange(n)
    rows = np.concatenate([d, r, c])
    cols = np.concatenate([d, c, r])
    vals = np.concatenate([np.full(n, 4.0 * row_nnz), v, v])
    shape = (n, n)
    out = {"rows": n, "entries": int(rows.shape[0]),
           "shards": int(get_mesh(None).devices.size)}

    # -- the sort alone (second run: sharding/compile setup warm) ----------
    ingest_coo_to_csr(rows, cols, vals, shape)
    t0 = _time.perf_counter()
    ingest_coo_to_csr(rows, cols, vals, shape)
    sort_s = max(_time.perf_counter() - t0, 1e-9)
    out["sort"] = {
        "wall_s": round(sort_s, 4),
        "rows_per_s": round(n / sort_s, 1),
        "entries_per_s": round(rows.shape[0] / sort_s, 1),
    }

    # -- cold onboarding: the whole data plane, compiles included ----------
    ses = SolveSession("cg")
    b = np.ones(n)
    try:
        snap = plan_cache.snapshot()
        t1 = ses.ingest((rows, cols, vals, shape), wait=True, timeout=600.0)
        cold_misses = plan_cache.delta(snap)["misses"]
        res1 = t1.result()
        t0 = _time.perf_counter()
        tk = ses.submit(res1["csr"], b, tol=1e-6)
        ses.drain()
        tk.result()
        cold_solve_ms = (_time.perf_counter() - t0) * 1e3
        out["cold"] = {
            "onboard_ms": t1.wall_ms,
            "rows_per_s": round(n / (t1.wall_ms / 1e3), 1),
            "first_solve_ms": round(cold_solve_ms, 3),
            "plan_misses": int(cold_misses),
        }

        # -- dedup-hit re-arrival: same structure, new values --------------
        snap = plan_cache.snapshot()
        t2 = ses.ingest((rows, cols, vals * 1.25, shape), wait=True,
                        timeout=600.0)
        res2 = t2.result()
        t0 = _time.perf_counter()
        tk = ses.submit(res2["csr"], b, tol=1e-6)
        ses.drain()
        tk.result()
        dedup_solve_ms = (_time.perf_counter() - t0) * 1e3
        dedup_misses = plan_cache.delta(snap)["misses"]
        out["dedup"] = {
            "onboard_ms": t2.wall_ms,
            "rows_per_s": round(n / (t2.wall_ms / 1e3), 1),
            "first_solve_ms": round(dedup_solve_ms, 3),
            "plan_misses": int(dedup_misses),
            "hit": bool(res2["dedup"]),
            "speedup": round(t1.wall_ms / max(t2.wall_ms, 1e-9), 2),
        }
        out["win"] = bool(
            res2["dedup"] and dedup_misses == 0
            and t2.wall_ms < t1.wall_ms
        )
        # flat headline keys: what axon_report lifts into metrics/trend
        out["sort_rows_per_s"] = out["sort"]["rows_per_s"]
        out["cold_onboard_ms"] = out["cold"]["onboard_ms"]
        out["dedup_onboard_ms"] = out["dedup"]["onboard_ms"]
        out["dedup_speedup"] = out["dedup"]["speedup"]
        out["dedup_plan_misses"] = out["dedup"]["plan_misses"]
    finally:
        if ses._onboarder is not None:
            ses._onboarder.close()
    return out


def run_sustained_cg(n: int = 512, B: int = 8, rate: float = 150.0,
                     duration: float = 1.5, slo_ms: float = 250.0,
                     seed: int = 23) -> dict:
    """Sustained-throughput row (ISSUE 11): drive a WARM ``SolveSession``
    through a fixed seeded Poisson arrival trace (``sparse_tpu.loadgen``)
    and report what the serving stack holds under open-loop load — the
    number the async front-end (ROADMAP item 1) will be judged against:

    * ``offered_rps`` vs ``achieved_rps``: the trace's arrival rate vs
      completed requests per wall second;
    * ``p50/p95/p99_ms``: end-to-end ticket latency through the real
      ticket path (submit -> coalesce -> bucketed dispatch -> resolve);
    * ``slo_miss_rate`` against the session's ``slo_ms`` objective
      (``p95_under_slo`` is the tracked acceptance bit).

    Warm by construction: the pattern pack and every pow2 bucket program
    the trace can hit are built before the measured window, so the row
    measures steady-state serving, not compile tax (``cold_start`` is
    the row for that). Embedded in the bench session record and lifted
    by ``scripts/axon_report.py`` onto the ``--compare`` surface as
    ``sustained_cg.{achieved_rps,p95_ms,slo_miss_rate}``.

    The pipeline comparison (ISSUE 13): a second, deliberately
    OVERLOADED seeded Poisson trace is played twice through two equally
    warm sessions — streaming dispatch on (``inflight`` from
    ``SPARSE_TPU_INFLIGHT``, floor 2) vs off (``inflight=1``, the
    classic enqueue->block loop) — and the achieved req/s land in the
    ``pipelined_rps`` / ``sync_rps`` columns with their p95/SLO-miss
    context; ``pipeline_speedup`` is their ratio, lifted onto the
    ``--compare`` surface by ``axon_report``.

    The continuous-telemetry tax (ISSUE 19): the same warm trace is
    replayed with the Axon v7 history sampler off vs on (over-sampled at
    20x the default interval) and the wall-clock delta lands in
    ``history_overhead_pct`` — the always-on sampler must stay under 2%.
    """
    import numpy as np
    import scipy.sparse as sp

    from sparse_tpu import loadgen
    from sparse_tpu.batch import SolveSession
    from sparse_tpu.config import settings as _settings

    rng = np.random.default_rng(seed)
    e = np.ones(n, dtype=np.float32)
    base = sp.diags(
        [-e[:-1], 2.5 * e, -e[:-1]], [-1, 0, 1], format="csr"
    ).astype(np.float32)
    mats = []
    for _ in range(B):
        Ai = base.copy()
        Ai.setdiag(2.5 + rng.random(n).astype(np.float32))
        Ai.sort_indices()
        mats.append(Ai.tocsr())
    rhs = rng.standard_normal((B, n)).astype(np.float32)
    systems = list(zip(mats, rhs))

    def warm_session(**kw):
        ses = SolveSession("cg", batch_max=32, slo_ms=slo_ms, **kw)
        pattern = ses.pattern_of(mats[0])
        pattern.sell_pack()
        # warm every bucket the coalescing can produce (pow2 up to
        # batch_max)
        bkt = 1
        while bkt <= ses.batch_max:
            ses._prebuild(pattern, "cg", bkt, np.dtype(np.float32))
            bkt *= 2
        return ses

    # sampled device profiling (ISSUE 12): every 4th dispatch records
    # its host-vs-device split so the bench row (and axon_report's
    # programs table) carries MEASURED device time, not just host wall
    ses = warm_session(profile_every=4)

    trace = loadgen.ArrivalTrace.poisson(
        rate=rate, duration=duration, seed=seed
    )
    rep = loadgen.run_load(ses, trace, systems, tol=1e-6)

    # -- pipeline on vs off on one overloaded seeded trace (ISSUE 13) --
    # the offered rate deliberately exceeds the sync path's service
    # rate, so achieved req/s measures the serving pipeline itself, not
    # the trace; identical trace + systems + warm state on both sides
    over = loadgen.ArrivalTrace.poisson(
        rate=rate * 4.0, duration=max(duration * 0.8, 0.5), seed=seed + 6
    )
    window = max(int(_settings.inflight), 2)
    rep_pipe = loadgen.run_load(
        warm_session(inflight=window), over, systems, tol=1e-6,
        pipeline=True,
    )
    rep_sync = loadgen.run_load(
        warm_session(inflight=1), over, systems, tol=1e-6,
        pipeline=False,
    )
    # -- history sampler overhead (ISSUE 19) ---------------------------
    # the same warm trace replayed with the continuous-telemetry sampler
    # off vs on (at 20x the default scrape rate, a deliberate stress
    # factor); the column is the wall-clock delta as a percentage of the
    # sampler-off run. Acceptance: < 2% at the default interval, which
    # this over-sampled replay bounds from above.
    hist_pct = None
    try:
        import shutil as _shutil
        import tempfile as _tempfile

        from sparse_tpu.telemetry import _history

        rep_off = loadgen.run_load(warm_session(), trace, systems,
                                   tol=1e-6)
        hroot = _tempfile.mkdtemp(prefix="bench_history_")
        _history.stop()
        _history.start(root=hroot, interval_s=0.05)
        try:
            rep_on = loadgen.run_load(warm_session(), trace, systems,
                                      tol=1e-6)
        finally:
            _history.stop()
            _shutil.rmtree(hroot, ignore_errors=True)
        hist_pct = round(
            (rep_on.wall_s / max(rep_off.wall_s, 1e-9) - 1.0) * 100.0, 2
        )
    except Exception:
        traceback.print_exc(file=sys.stderr)

    # the measured device-time rollup of the sampled dispatches (the
    # cost table accumulates per-program; aggregate across buckets)
    dev_ms = dev_n = 0.0
    try:
        from sparse_tpu.telemetry import _cost

        for p in _cost.programs().values():
            if p.get("device_samples"):
                dev_ms += p["device_ms_total"]
                dev_n += p["device_samples"]
    except Exception:
        traceback.print_exc(file=sys.stderr)
    return {
        **({"device_ms_mean": round(dev_ms / dev_n, 3),
            "device_samples": int(dev_n)} if dev_n else {}),
        **({"history_overhead_pct": hist_pct}
           if hist_pct is not None else {}),
        "n": n, "rate": rate, "duration_s": duration,
        "trace": rep.trace,
        "arrivals": rep.arrivals, "completed": rep.completed,
        "failed": rep.failed,
        "offered_rps": rep.offered_rps,
        "achieved_rps": rep.achieved_rps,
        "p50_ms": rep.latency_ms["p50"],
        "p95_ms": rep.latency_ms["p95"],
        "p99_ms": rep.latency_ms["p99"],
        "slo_ms": slo_ms,
        "slo_misses": rep.slo_misses,
        "slo_miss_rate": rep.slo_miss_rate,
        "p95_under_slo": rep.latency_ms["p95"] <= slo_ms,
        "dispatches": rep.dispatches,
        "wall_s": rep.wall_s,
        # the streaming-dispatch comparison (ISSUE 13): same overloaded
        # seeded trace, pipeline on (SPARSE_TPU_INFLIGHT window) vs off.
        # host_cores contextualizes the speedup — overlap needs a core
        # for the host ALONGSIDE the XLA compute pool, so a 1-core
        # container reads ~1.0 by construction while a real serving
        # host shows the pack/solve overlap
        "host_cores": os.cpu_count() or 1,
        "inflight": window,
        "pipelined_rps": rep_pipe.achieved_rps,
        "sync_rps": rep_sync.achieved_rps,
        "pipeline_speedup": round(
            rep_pipe.achieved_rps / max(rep_sync.achieved_rps, 1e-9), 3
        ),
        "pipelined_p95_ms": rep_pipe.latency_ms["p95"],
        "sync_p95_ms": rep_sync.latency_ms["p95"],
        "pipelined_slo_miss_rate": rep_pipe.slo_miss_rate,
        "sync_slo_miss_rate": rep_sync.slo_miss_rate,
        "pipelined_inflight_depth": rep_pipe.inflight_depth,
    }


def run_spmm(n: int = 2000, width: int = 128):
    """SpMM row (VERDICT r3 #7): CSR x dense WIDE B — the MXU-shaped op
    the reference implements as a first-class task family
    (src/sparse/array/csr/spmm.cu, 648 LoC) but this bench never
    measured. Returns GFLOP/s on the n^2-row 5-point Laplacian at the
    given B width (f32)."""
    import jax.numpy as jnp

    from sparse_tpu.models.poisson import laplacian_2d_ell
    from sparse_tpu.ops.spmv import csr_spmm_ell

    N = n * n
    ell_idx, ell_val = laplacian_2d_ell(n)
    nnz = int(jnp.sum(ell_val != 0))
    B = jnp.ones((N, width), dtype=jnp.float32)
    flops = 2.0 * nnz * width
    sec = _time_kernel(lambda BB: csr_spmm_ell(ell_idx, ell_val, BB), B)
    return flops / sec / 1e9


SPMV_BASELINE_ITERS_PER_S = 347.7  # reference: 10M rows, 11-diag banded, f64, 1x V100


def run_spmv_11diag(rows: int = 10_000_000, plane_dtype=None, tile=None):
    """The reference's CSR SpMV microbenchmark shape (BASELINE.md row 1):
    banded 11 nnz/row at 10M rows — here in the prepared DIA layout
    (planes packed once, like the reference's resident CSR stores).
    ``plane_dtype=jnp.bfloat16`` streams the planes at half width (exact
    here: the values are ones); the f32 row stays the headline. Returns
    ``(iters_per_s, tile_used, band)`` where ``band`` maps probed tiles to
    best-of-chain seconds/SpMV (empty when ``tile`` was given or autotune
    was inert)."""
    import jax.numpy as jnp

    from sparse_tpu.kernels.dia_spmv import PreparedDia, autotune_dia_tile

    offsets = tuple(range(-5, 6))
    planes = jnp.ones((11, rows), dtype=plane_dtype or jnp.float32)
    x = jnp.ones((rows,), dtype=jnp.float32)
    band = {}
    if tile is None:
        tile, band = autotune_dia_tile(planes, offsets, (rows, rows))
    prep = PreparedDia(planes, offsets, (rows, rows), tile=tile)
    # reps=8: the shared-tunnel backend shows multi-second throughput swings
    # (measured 405-972 iters/s across runs of this row); a sub-ms kernel
    # needs the extra best-of samples to land in the device's real band.
    return 1.0 / _time_kernel(prep, x, reps=8), tile, band


def run_fused(n: int, iters: int, tiles=(65536, 131072, 16384)):
    # 131072 added after the r3 tile sweep: the packed-DIA SpMV's best
    # band moved to the larger tile on current hardware (147 GFLOP/s vs
    # 138 at 64k); the fused sweep keeps 64k first (known-best for CG).
    """Fused CG iterations/second (kernels/cg_dia.py).

    Sweeps {two-pass, one-pass Chronopoulos-Gear} x row-tile sizes and
    keeps the fastest variant whose final squared residual rho = ||r||^2
    stays within 10x of the two-pass reference (~3.2x in norm — guards
    against a variant silently diverging on hardware). Returns
    (best_iters_per_s, variant_label).
    """
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparse_tpu.kernels.cg_dia import cg_dia_fused, cg_dia_fused_onepass
    from sparse_tpu.models.poisson import laplacian_2d_dia
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    xtrue = jax.random.normal(jax.random.PRNGKey(0), (N,), jnp.float32)
    b = dia_spmv_xla(planes, offsets, xtrue, (N, N))
    best, label = 0.0, ""
    rho_ref = None
    # bf16 plane streaming is tried only when EXACT (stencil coefficients
    # representable with zero loss) — halves matrix traffic, same result
    exact_bf16 = bool(
        jnp.all(planes == planes.astype(jnp.bfloat16).astype(planes.dtype))
    )
    variants = [(cg_dia_fused, "twopass", None), (cg_dia_fused_onepass, "onepass", None)]
    if exact_bf16:
        variants += [
            (cg_dia_fused_onepass, "onepass_bf16", jnp.bfloat16),
            (cg_dia_fused, "twopass_bf16", jnp.bfloat16),
        ]
    for fn, name, pdt in variants:
        for tile in tiles:
            if pdt is not None and tile > 65536:
                # measured on v5e: bf16 plane scratch at the 128k tile
                # exceeds the 16M scoped-vmem limit (18.02M) — a
                # deterministic compile failure, skip the budget burn
                continue
            try:
                out = fn(
                    planes, offsets, b, None, N, iters=iters, tile=tile,
                    plane_dtype=pdt,
                )
                rho = float(out[2])  # compile + warm (+ convergence proxy)
                if rho_ref is None and name == "twopass" and np.isfinite(rho):
                    rho_ref = rho
                # no finite two-pass reference => only isfinite-gate the
                # rest, and say so rather than silently trusting them
                if rho_ref is None and name != "twopass":
                    print(
                        "bench: no finite two-pass rho reference; "
                        f"{name} tile={tile} gated on isfinite only",
                        file=sys.stderr,
                    )
                if not np.isfinite(rho) or (
                    rho_ref is not None and rho > 10 * max(rho_ref, 1e-30)
                ):
                    print(
                        f"bench: fused {name} tile={tile} rho={rho} fails "
                        f"parity vs {rho_ref}; skipping",
                        file=sys.stderr,
                    )
                    continue
                for _ in range(3):
                    t0 = time.perf_counter()
                    out = fn(
                        planes, offsets, b, None, N, iters=iters, tile=tile,
                        plane_dtype=pdt,
                    )
                    float(out[2])
                    v = iters / (time.perf_counter() - t0)
                    if v > best:
                        best, label = v, f"{name}_t{tile}"
            except Exception:
                traceback.print_exc(file=sys.stderr)
                print(f"bench: fused {name} tile={tile} failed; next", file=sys.stderr)
    if not label:  # nothing measured: report absence, not a fake 0.0
        return None
    return best, label


def run_fused_headline(n: int, iters: int, tile: int = 65536):
    """ONE fused-CG variant — the known-best twopass/tile geometry from the
    r2 hardware sweep — measured first so the headline exists within ~2
    compiles of worker start. Gated on a finite residual. Returns
    iters/s or None."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from sparse_tpu.kernels.cg_dia import cg_dia_fused
    from sparse_tpu.models.poisson import laplacian_2d_dia
    from sparse_tpu.ops.dia_spmv import dia_spmv_xla

    N = n * n
    planes, offsets = laplacian_2d_dia(n)
    xtrue = jax.random.normal(jax.random.PRNGKey(0), (N,), jnp.float32)
    b = dia_spmv_xla(planes, offsets, xtrue, (N, N))
    out = cg_dia_fused(planes, offsets, b, None, N, iters=iters, tile=tile)
    rho = float(out[2])  # compile + warm + convergence proxy
    if not np.isfinite(rho):
        print(f"bench: fused headline rho={rho} not finite", file=sys.stderr)
        return None
    best = 0.0
    for _ in range(3):
        t0 = time.perf_counter()
        out = cg_dia_fused(planes, offsets, b, None, N, iters=iters, tile=tile)
        float(out[2])
        best = max(best, iters / (time.perf_counter() - t0))
    return best


def _vs_pde(v: float, n: int) -> float:
    return round(
        (v * n * n) / (BASELINE_ITERS_PER_S * BASELINE_N * BASELINE_N), 3
    )


def worker(platform_arg: str) -> None:
    """Run the measurement on one platform; print the JSON line on success.

    platform_arg: 'default' (whatever the environment provides, e.g. the
    TPU tunnel) or 'cpu' (forced before the jax import).

    TPU stage order (best number first, checkpoint after every stage —
    the parent parses the LAST metric line, so a timeout/fault in a later
    stage can never lose an earlier measurement):
      1. fused CG @6000^2, the single known-best variant   -> headline
      2. step-loop CG (fallback headline + comparison row)
      3. 11-diag SpMV microbenchmark (f32 + bf16)
      4. kernel GFLOPS sweep
      5. full fused variant sweep (refines the headline if better)
    """
    if platform_arg == "cpu":
        # the axon plugin overrides the env var; set the config knob too
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
    else:
        import jax

    from sparse_tpu.config import settings as _settings
    from sparse_tpu.utils import enable_compilation_cache

    # reruns skip the 20-40 s tunnel compiles; SPARSE_TPU_COMPILE_CACHE
    # (the serving-path knob, ISSUE 9 satellite) overrides the location
    enable_compilation_cache(_settings.compile_cache or None)

    platform = jax.devices()[0].platform
    _telemetry_models_stage(platform)
    if platform != "cpu":
        rec = None
        n = 6000
        for n_try in (6000, 4000, 2000):
            try:  # stage 1: fused headline
                fused = run_fused_headline(n_try, ITERS)
            except Exception:
                traceback.print_exc(file=sys.stderr)
                fused = None
            if fused:
                n = n_try
                rec = {
                    "metric": f"cg_iters_per_s_pde{n}_{platform}_fused",
                    "value": round(fused, 2),
                    "unit": "iters/s",
                    "vs_baseline": _vs_pde(fused, n),
                    "fused_cg_iters_per_s": round(fused, 2),
                    "fused_cg_variant": "twopass_t65536",
                }
                print(json.dumps(rec))
                sys.stdout.flush()
                break
        for n_try in ((n,) if rec else (6000, 4000, 2000, 512)):
            try:  # stage 2: step-loop CG
                best = run_size(n_try, ITERS)
            except Exception:
                traceback.print_exc(file=sys.stderr)
                print(f"bench worker: size {n_try} failed", file=sys.stderr)
                continue
            if rec is None:
                n = n_try
                rec = {
                    "metric": f"cg_iters_per_s_pde{n}_{platform}",
                    "value": round(best, 2),
                    "unit": "iters/s",
                    "vs_baseline": _vs_pde(best, n),
                }
            rec["step_loop_iters_per_s"] = round(best, 2)
            print(json.dumps(rec))
            sys.stdout.flush()
            break
        if rec is None:
            sys.exit(3)  # every size failed on both paths
        try:  # stage 3: the reference's SpMV microbenchmark row (347.7)
            v, tile, band = run_spmv_11diag()
            rec["spmv_11diag_iters_per_s"] = round(v, 1)
            rec["spmv_11diag_vs_baseline"] = round(
                v / SPMV_BASELINE_ITERS_PER_S, 2
            )
            # autotune trace: the tile this session picked plus the probed
            # band, so round artifacts show WHERE in the 24-147 GFLOP/s
            # range the session sits and whether the choice is stable
            rec["spmv_11diag_tile"] = tile
            if band:
                rec["spmv_11diag_tile_band_us"] = {
                    str(t): round(s * 1e6, 1) for t, s in band.items()
                }
            import jax.numpy as jnp

            # bf16 row reuses the f32 winner: its timing comes from
            # _time_kernel anyway, a second autotune probe (fresh cache
            # key, up to two cold Mosaic compiles) buys nothing
            vb, _, _ = run_spmv_11diag(plane_dtype=jnp.bfloat16, tile=tile)
            rec["spmv_11diag_bf16_iters_per_s"] = round(vb, 1)
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 3.5: SpMM (CSR x wide dense, MXU-shaped) row
            sw = min(n, 2000)
            rec["spmm_gflops"] = round(run_spmm(sw, 128), 1)
            rec["spmm_shape"] = f"laplacian{sw}x{sw}_B128_f32"
        except Exception:
            traceback.print_exc(file=sys.stderr)
        print(json.dumps(rec))
        sys.stdout.flush()
        try:  # stage 4: per-kernel GFLOPS/roofline diagnostics
            sweep_n = min(n, 2000)
            rec["kernels"] = kernel_sweep(sweep_n, platform)
            rec["kernels_n"] = sweep_n
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.5: skewed-degree general-matrix CG (prepared SELL)
            rec["skewed_cg"] = run_skewed_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.6: batched same-pattern solves (sparse_tpu.batch)
            rec["batched_cg"] = run_batched_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.7: vault cold/disk-warm/warm restart row (ISSUE 9)
            rec["cold_start"] = run_cold_start()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.8: sustained-throughput loadgen row (ISSUE 11)
            rec["sustained_cg"] = run_sustained_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.9: batched preconditioner row (ISSUE 14)
            rec["precond_cg"] = run_precond_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.10: mixed-precision row (ISSUE 15)
            rec["mixed_cg"] = run_mixed_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.11: autopilot policy-tuning row (ISSUE 16)
            rec["auto_cg"] = run_auto_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # stage 4.12: ingest data-plane row (ISSUE 18)
            rec["ingest"] = run_ingest()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        print(json.dumps(rec))
        sys.stdout.flush()
        try:  # stage 5: full fused sweep — refines the headline if better
            fused_result = run_fused(n, ITERS)
            if fused_result:
                fused, fused_label = fused_result
                if fused > rec.get("fused_cg_iters_per_s", 0.0):
                    rec["fused_cg_iters_per_s"] = round(fused, 2)
                    rec["fused_cg_variant"] = fused_label
                if fused > rec["value"]:
                    rec["value"] = round(fused, 2)
                    rec["vs_baseline"] = _vs_pde(fused, n)
                    rec["metric"] = f"cg_iters_per_s_pde{n}_{platform}_fused"
        except Exception:
            traceback.print_exc(file=sys.stderr)
        print(json.dumps(rec))
        sys.stdout.flush()
        return

    # cpu fallback: small, fast, zero-compile-risk salvage line
    for n in (512,):
        try:
            best = run_size(n, ITERS)
        except Exception:
            traceback.print_exc(file=sys.stderr)
            print(f"bench worker: size {n} failed", file=sys.stderr)
            continue
        rec = {
            "metric": f"cg_iters_per_s_pde{n}_{platform}",
            "value": round(best, 2),
            "unit": "iters/s",
            "vs_baseline": _vs_pde(best, n),
        }
        try:
            rec["kernels"] = kernel_sweep(256, platform)
            rec["kernels_n"] = 256
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # skewed-degree CSR CG: the tracked prepared-SELL number
            rec["skewed_cg"] = run_skewed_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # batched same-pattern solves: the tracked microbatching row
            rec["batched_cg"] = run_batched_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # vault cold/disk-warm/warm restart row (ISSUE 9)
            rec["cold_start"] = run_cold_start()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # sustained-throughput loadgen row (ISSUE 11, the CPU lane)
            rec["sustained_cg"] = run_sustained_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # batched preconditioner row (ISSUE 14, the CPU lane)
            rec["precond_cg"] = run_precond_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # mixed-precision row (ISSUE 15, the CPU lane)
            rec["mixed_cg"] = run_mixed_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # autopilot policy-tuning row (ISSUE 16, the CPU lane)
            rec["auto_cg"] = run_auto_cg()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        try:  # ingest data-plane row (ISSUE 18, the CPU lane)
            rec["ingest"] = run_ingest()
        except Exception:
            traceback.print_exc(file=sys.stderr)
        print(json.dumps(rec))
        sys.stdout.flush()
        return
    sys.exit(3)  # every size failed


def probe() -> None:
    """--probe mode: report whether the default backend is a live TPU.

    Runs in a subprocess under a hard watchdog — a wedged tunnel hangs in
    backend init and the PARENT decides it's dead by timeout. Prints one
    JSON line {"platform": ..., "alive": true} on success."""
    import jax
    import jax.numpy as jnp

    d = jax.devices()[0]
    # one tiny op end-to-end: backend that enumerates devices but cannot
    # execute (half-wedged tunnel) must fail the probe too
    v = float(jnp.sum(jnp.ones((8, 8)) * 2.0))
    assert v == 128.0
    print(json.dumps({"platform": d.platform, "alive": True}))


GMG_BASELINE_ITERS_PER_S = 37.2  # reference: 4500^2/GPU V-cycle CG, 1x V100
GMG_BASELINE_N = 4500


def _run_example(script: str, attempts, timeout_s: int, keep_trying=False,
                 log_name=None):
    """Run an example script as a subprocess for each arg-list in
    ``attempts`` until one yields an "Iterations / sec" line; returns
    (value, attempt_index, mean_value_or_None) or None — the third slot
    carries the "Iterations / sec (mean):" line when the script prints
    one (the GMG dual-estimator row). Shared scaffold for the GMG and
    quantum bench rows.

    ``timeout_s`` is a TOTAL deadline across all attempts, not per
    attempt — two sequential timed-out attempts must not overshoot the
    caller's remaining budget (observed: GMG 4500 then 2000, each given
    the full window, blew ~190s past BENCH_BUDGET_S).

    ``keep_trying``: attempts are ordered cheap -> impressive; bank the
    first success and keep upgrading while budget remains (the quantum
    row's 1e5-state shape repeatedly starved its own fallbacks when
    tried first)."""
    import re

    deadline = time.monotonic() + timeout_s
    here = os.path.dirname(os.path.abspath(__file__))
    got = None
    for i, args in enumerate(attempts):
        left = deadline - time.monotonic()
        if left < 60:
            print(f"bench: {script} out of budget before {args}", file=sys.stderr)
            break
        # fair-share so a hung large-size attempt can't starve the
        # fallback sizes of their chance at a completed row
        share = max(90.0, left / (len(attempts) - i))
        try:
            proc = subprocess.run(
                [sys.executable, os.path.join(here, "examples", script), *args],
                capture_output=True,
                text=True,
                timeout=min(left, share),
                cwd=here,
            )
        except subprocess.TimeoutExpired:
            print(f"bench: {script} {args} timed out", file=sys.stderr)
            _note_probe_timeout(script, min(left, share))
            continue
        if proc.returncode != 0:
            sys.stderr.write(proc.stderr[-2000:])
            continue
        m = re.search(r"Iterations / sec: ([0-9.]+)", proc.stdout)
        if m:
            if log_name is not None:  # verbatim evidence (results/axon/*.out)
                _log_hw_text(
                    f"{log_name}_{'_'.join(a.lstrip('-') for a in args[:4])}",
                    proc.stdout,
                )
            mm = re.search(r"Iterations / sec \(mean\): ([0-9.]+)", proc.stdout)
            got = (float(m.group(1)), i, float(mm.group(1)) if mm else None)
            if not keep_trying:
                return got
    return got


def _try_pde(timeout_s: int = 600):
    """PUBLIC-API PDE headline: examples/pde.py -throughput — the
    reference's exact command shape (results/summit/legate_gpu_pde.out:2,
    75.9 iters/s at 6000^2/V100). The inlined fused-CG stage above
    measures the kernels; this row proves the same throughput arrives
    through `linalg.cg` on the public surface (VERDICT r3 #3)."""
    sizes = (2000, 6000)
    got = _run_example(
        "pde.py",
        [
            # size-leading args: the evidence-log filename is built from
            # args[:4], so the nx/ny pair must land in it
            ["-nx", str(n), "-ny", str(n), "-throughput", "-max_iter", "300",
             "--precision", "f32"]
            for n in sizes
        ],
        timeout_s,
        keep_trying=True,
        log_name="pde",
    )
    if got is None:
        return None
    v, i, v_mean = got
    n = sizes[i]
    out = {
        f"pde_public_api_iters_per_s_n{n}": round(v, 2),
        "pde_public_api_vs_baseline": _vs_pde(v, n),
    }
    if v_mean is not None:
        out[f"pde_public_api_iters_per_s_n{n}_mean"] = round(v_mean, 2)
        out["pde_public_api_vs_baseline_mean"] = _vs_pde(v_mean, n)
    return out


def _try_gmg(timeout_s: int = 600):
    """Run the GMG example (BASELINE.md row 3) and parse iters/s. Runs
    AFTER the headline worker exits (sequential TPU clients — the tunnel
    serves one process at a time). Falls back to a smaller grid; baseline
    comparison is row-normalized like run_size."""
    # cheap -> impressive with keep_trying: bank 2000, upgrade to 4000,
    # then the reference's EXACT 4500 shape (direct comparison, no row
    # normalization) — feasible in-budget since the structured-grid
    # pipeline (models/gmg_grid.py) cut init from ~52 s of COO sorts +
    # eager power iteration to a few seconds of compiled probing.
    sizes = ((2000, 5), (4000, 6), (4500, 6))
    if os.environ.get("BENCH_GMG_SIZES"):  # test hook: "n:levels,n:levels"
        sizes = tuple(
            (int(a), int(b))
            for a, b in (
                s.split(":") for s in os.environ["BENCH_GMG_SIZES"].split(",")
            )
        )
    got = _run_example(
        "gmg.py",
        [
            ["-n", str(n), "-levels", str(lv), "-maxiter", "200",
             "--precision", "f32"]  # TPU-native dtype (f64 is emulated)
            for n, lv in sizes
        ],
        timeout_s,
        keep_trying=True,
        log_name="gmg",
    )
    if got is None:
        return None
    v, i, v_mean = got
    n = sizes[i][0]
    vs = (v * n * n) / (
        GMG_BASELINE_ITERS_PER_S * GMG_BASELINE_N * GMG_BASELINE_N
    )
    out = {
        f"gmg_iters_per_s_n{n}": round(v, 2),
        "gmg_vs_baseline": round(vs, 3),
    }
    if v_mean is not None:
        # same-estimator comparison (the reference baseline is a mean):
        # recorded alongside the min-of-2 machine-capability headline
        out[f"gmg_iters_per_s_n{n}_mean"] = round(v_mean, 2)
        out["gmg_vs_baseline_mean"] = round(
            (v_mean * n * n)
            / (GMG_BASELINE_ITERS_PER_S * GMG_BASELINE_N * GMG_BASELINE_N),
            3,
        )
    return out


def _try_quantum(timeout_s: int = 420):
    """Run the quantum MIS evolution example (BASELINE.md row 4) and parse
    iters/s. Recorded WITHOUT a vs_baseline ratio: the reference's 1.85
    iters/s drives an external Rydberg-lattice script
    (scripts/summit/run_legate_quantum.sh) whose problem shape we don't
    replicate; the metric documents our absolute throughput on the
    ER-graph analog (examples/quantum_evolution.py)."""
    attempts = (
        # cheap -> impressive with keep_trying: bank the ER-16 row
        # (~60 s warm), then upgrade to the >=1e5-state scale shape
        # (cycle_graph(25): 167,761 independent sets, VERDICT r2 #10)
        ["-nodes", "16", "-t", "1.0", "--precision", "f32"],
        ["-graph", "cycle", "-nodes", "25", "-t", "0.05", "--precision", "f32"],
    )
    labels = ("nodes16", "cycle25")
    got = _run_example(
        "quantum_evolution.py", list(attempts), timeout_s, keep_trying=True,
        log_name="quantum",
    )
    if got is None:
        return None
    v, i, _ = got
    return {f"quantum_iters_per_s_{labels[i]}": v}


def _try_amg(timeout_s: int = 420):
    """Run the AMG example (the reference's north-star workload; no
    single-chip baseline row exists in BASELINE.md, so the metric is
    absolute like the quantum row). cheap -> impressive with
    keep_trying; hierarchy setup is CPU-phase (native Gustavson)."""
    attempts = (
        ["-n", "256", "-maxiter", "100", "--precision", "f32"],
        ["-n", "512", "-maxiter", "100", "--precision", "f32"],
    )
    labels = ("n256", "n512")
    got = _run_example(
        "amg.py", list(attempts), timeout_s, keep_trying=True, log_name="amg"
    )
    if got is None:
        return None
    v, i, _ = got
    return {f"amg_iters_per_s_{labels[i]}": v}


def _try_multichip_comm(timeout_s: float):
    """Multichip measured-comm lane (ISSUE 7): run the S=8 CPU dryrun's
    collective-accounting stage (``__graft_entry__.dryrun_comm``) in a
    subprocess and return its structured stats — measured vs model bytes
    per shard for halo- and gather-mode ``dist_cg`` plus the <=10%
    agreement verdict. CPU-only by construction (the dryrun forces the
    virtual mesh), so it runs on every platform without touching a
    fragile tunnel. Returns the parsed dict, or None."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the tunnel for this
    try:
        proc = subprocess.run(
            [
                sys.executable, "-c",
                "import __graft_entry__ as g; g.dryrun_comm(8)",
            ],
            capture_output=True,
            text=True,
            timeout=max(60, timeout_s),
            cwd=HERE,
            env=env,
        )
    except subprocess.TimeoutExpired:
        _note_probe_timeout("multichip_comm", timeout_s)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("MULTICHIP_COMM_JSON: "):
            try:
                return json.loads(line[len("MULTICHIP_COMM_JSON: "):])
            except json.JSONDecodeError:
                break
    sys.stderr.write(proc.stderr[-1500:])
    print(
        f"bench: multichip comm dryrun rc={proc.returncode} without stats",
        file=sys.stderr,
    )
    return None


def _try_fleet(timeout_s: float):
    """Fleet serving lane (ISSUE 10): run the S=8 ``fleet_batched_cg``
    scenario (``__graft_entry__.dryrun_fleet``) in a subprocess and
    return its structured row — sharded vs single-device wall times on
    the batched_cg workload, per-lane parity at machine eps, and the
    measured-vs-model psum accounting with its <=10% verdict. CPU-only
    by construction (the dryrun forces the virtual mesh). Returns the
    parsed dict, or None."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the tunnel for this
    try:
        proc = subprocess.run(
            [
                sys.executable, "-c",
                "import __graft_entry__ as g; g.dryrun_fleet(8)",
            ],
            capture_output=True,
            text=True,
            timeout=max(60, timeout_s),
            cwd=HERE,
            env=env,
        )
    except subprocess.TimeoutExpired:
        _note_probe_timeout("fleet_batched_cg", timeout_s)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("MULTICHIP_FLEET_JSON: "):
            try:
                return json.loads(line[len("MULTICHIP_FLEET_JSON: "):])
            except json.JSONDecodeError:
                break
    sys.stderr.write(proc.stderr[-1500:])
    print(
        f"bench: fleet dryrun rc={proc.returncode} without stats",
        file=sys.stderr,
    )
    return None


def _try_platform(platform_arg: str, timeout_s: int):
    """Run a worker subprocess; return its parsed JSON line or None."""
    stdout, stderr, rc = "", "", None
    env = dict(os.environ)
    if platform_arg == "cpu":
        # the axon sitecustomize hook dials the TPU tunnel from every
        # process whose env carries this var — on a wedged tunnel that
        # registration blocks for minutes before giving up, defeating the
        # point of the cpu FALLBACK (same trick as tests/conftest.py)
        env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker", platform_arg],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
            env=env,
        )
        stdout, stderr, rc = proc.stdout, proc.stderr, proc.returncode
    except subprocess.TimeoutExpired as e:
        # the worker checkpoints its record before slow optional sweeps —
        # salvage the last metric line from the partial output
        print(
            f"bench: platform {platform_arg!r} timed out after {timeout_s}s; "
            "salvaging partial output",
            file=sys.stderr,
        )
        _note_probe_timeout(f"worker:{platform_arg}", timeout_s)
        def _dec(v):
            return v.decode(errors="replace") if isinstance(v, bytes) else (v or "")

        stdout, stderr = _dec(e.stdout), _dec(e.stderr)
    sys.stderr.write(stderr[-4000:])
    for line in reversed(stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
            if "metric" in rec:
                return rec
        except (json.JSONDecodeError, TypeError):
            continue
    print(
        f"bench: platform {platform_arg!r} exited rc={rc} "
        "without a metric line",
        file=sys.stderr,
    )
    return None


PROBE_TIMEOUTS: list = []  # [{"probe", "timeout_s", "t_wall"}] this run


def _note_probe_timeout(probe: str, timeout_s: float) -> None:
    """Structured record of a watchdog-killed probe (ISSUE 6 satellite:
    'probe timed out' used to be a bare stderr line — three in the
    BENCH_r05 tail — invisible to every session artifact). The entries
    land in the session record's ``timeouts`` field; with telemetry on
    each is also a ``bench.probe_timeout`` event, emitted by
    ``_log_session_record`` (not here) so a mid-run timeout cannot wedge
    the bench on a first jax/sparse_tpu import."""
    PROBE_TIMEOUTS.append({
        "probe": probe,
        "timeout_s": round(float(timeout_s), 1),
        "t_wall": round(time.time(), 3),
    })


def _probe_tpu(timeout_s: float) -> str:
    """Run the --probe subprocess. Returns one of:
    'tpu'  — a live non-cpu backend answered within the watchdog;
    'cpu'  — the backend healthily reports CPU (no tunnel configured:
             re-probing cannot conjure a TPU, don't burn budget on it);
    'dead' — timeout/crash (the wedged-tunnel signature: worth re-probing,
             tunnels have been observed to recover mid-run)."""
    timeout_s = max(10.0, timeout_s)
    try:
        proc = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--probe"],
            capture_output=True,
            text=True,
            timeout=timeout_s,
            cwd=os.path.dirname(os.path.abspath(__file__)),
        )
    except subprocess.TimeoutExpired:
        print(f"bench: probe timed out after {timeout_s:.0f}s", file=sys.stderr)
        _note_probe_timeout("tpu", timeout_s)
        return "dead"
    for line in reversed(proc.stdout.strip().splitlines()):
        try:
            rec = json.loads(line)
        except json.JSONDecodeError:
            continue
        if rec.get("alive"):
            print(f"bench: probe sees {rec['platform']}", file=sys.stderr)
            return "tpu" if rec["platform"] != "cpu" else "cpu"
    sys.stderr.write(proc.stderr[-1500:])
    print(f"bench: probe rc={proc.returncode}, backend dead", file=sys.stderr)
    return "dead"


PROBE_TIMEOUT_S = 120.0
# a late TPU attempt needs ~2 compiles (~40s each through the tunnel,
# near-zero with a warm .jax_cache) + 3 timed reps + headroom
MIN_TPU_ATTEMPT_S = 240.0


def _try_remesh(timeout_s: float):
    """Elastic-mesh lane (ISSUE 20): run the S=8 -> S=4 -> S=8
    ``elastic_remesh`` scenario (``__graft_entry__.dryrun_remesh``) in a
    subprocess and return its structured row — time-to-first-solve after
    a shrink (cold vs mesh-keyed-manifest-warm re-plan), the zero-miss
    warm-shrink gate, and the zero-loss in-flight migration verdict.
    CPU-only by construction (the dryrun forces the virtual mesh).
    Returns the parsed dict, or None."""
    env = dict(os.environ)
    env.pop("PALLAS_AXON_POOL_IPS", None)  # never dial the tunnel for this
    try:
        proc = subprocess.run(
            [
                sys.executable, "-c",
                "import __graft_entry__ as g; g.dryrun_remesh(8)",
            ],
            capture_output=True,
            text=True,
            timeout=max(60, timeout_s),
            cwd=HERE,
            env=env,
        )
    except subprocess.TimeoutExpired:
        _note_probe_timeout("elastic_remesh", timeout_s)
        return None
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith("MULTICHIP_REMESH_JSON: "):
            try:
                return json.loads(line[len("MULTICHIP_REMESH_JSON: "):])
            except json.JSONDecodeError:
                break
    sys.stderr.write(proc.stderr[-1500:])
    print(
        f"bench: remesh dryrun rc={proc.returncode} without stats",
        file=sys.stderr,
    )
    return None


def main():
    t_start = time.monotonic()
    budget_s = float(os.environ.get("BENCH_BUDGET_S", "870"))
    # parse eagerly so a malformed value fails fast HERE, before hours of
    # benchmarking — but the module contract (a metric line is ALWAYS
    # printed) holds even then: emit an explicit error record, then raise
    try:
        session_log_max_age_s = float(
            os.environ.get("BENCH_SESSION_LOG_MAX_AGE_S", "172800")
        )
    except ValueError:
        print(json.dumps({
            "metric": "bench_config_error", "value": 0.0, "unit": "none",
            "vs_baseline": 0.0,
            "error": "malformed BENCH_SESSION_LOG_MAX_AGE_S",
        }))
        sys.stdout.flush()
        raise

    def remaining():
        return budget_s - (time.monotonic() - t_start)

    rec = None
    status = "dead"
    try:
        # the probe (~120s watchdog) decides whether the TPU attempt may
        # run at all — a wedged backend init can no longer burn the whole
        # budget before the CPU fallback gets a chance (VERDICT r2 #1)
        status = _probe_tpu(min(PROBE_TIMEOUT_S, remaining() - 60))
        if status == "tpu":
            rec = _try_platform("default", max(60, remaining() - 90))
        if rec is None:
            # dead/wedged tunnel (or TPU worker failure): salvage the CPU
            # line NOW. Then — only for the wedged-tunnel signature
            # ('dead', not a healthy cpu-only answer) — keep probing, so a
            # late tunnel recovery still yields a TPU line within budget.
            rec = _try_platform("cpu", min(420, max(60, remaining() - 30)))
            if rec is not None:
                print(json.dumps(rec))
                sys.stdout.flush()
            while (
                status == "dead"
                and remaining() > PROBE_TIMEOUT_S + MIN_TPU_ATTEMPT_S
            ):
                time.sleep(min(30, max(0, remaining() - MIN_TPU_ATTEMPT_S)))
                status = _probe_tpu(PROBE_TIMEOUT_S)
                if status == "tpu":
                    trec = _try_platform("default", remaining() - 30)
                    if trec is not None and "_tpu" in trec.get("metric", ""):
                        rec = trec
                        break
        if rec is not None:
            # checkpoint BEFORE the slow example phases: a hard kill during
            # GMG/quantum must not lose the headline (finally does not
            # survive SIGKILL; the driver reads the LAST metric line)
            print(json.dumps(rec))
            sys.stdout.flush()
        if rec is not None and remaining() > 150:
            try:  # multichip measured-comm lane — structured, never fatal
                mc = _try_multichip_comm(min(240, remaining() - 60))
                if mc:
                    rec["multichip_comm"] = mc
                    if not mc.get("ok"):
                        print(
                            "bench: multichip measured-vs-model comm "
                            "DIVERGED beyond tolerance: "
                            + json.dumps(mc.get("modes", {})),
                            file=sys.stderr,
                        )
                    print(json.dumps(rec))
                    sys.stdout.flush()
            except Exception:
                traceback.print_exc(file=sys.stderr)
        if rec is not None and remaining() > 150:
            try:  # fleet serving lane (ISSUE 10) — structured, never fatal
                fl = _try_fleet(min(300, remaining() - 60))
                if fl:
                    rec["fleet_batched_cg"] = fl
                    if not fl.get("ok"):
                        print(
                            "bench: fleet_batched_cg FAILED its parity/"
                            "comm gates: " + json.dumps({
                                k: fl.get(k) for k in (
                                    "max_abs_diff", "divergence_pct",
                                    "iters_equal",
                                )
                            }),
                            file=sys.stderr,
                        )
                    print(json.dumps(rec))
                    sys.stdout.flush()
            except Exception:
                traceback.print_exc(file=sys.stderr)
        if rec is not None and remaining() > 150:
            try:  # elastic remesh lane (ISSUE 20) — structured, never fatal
                el = _try_remesh(min(300, remaining() - 60))
                if el:
                    rec["remesh"] = el
                    if not el.get("ok"):
                        print(
                            "bench: elastic_remesh FAILED its zero-loss/"
                            "warm-replan gates: " + json.dumps({
                                k: el.get(k) for k in (
                                    "tickets_preserved",
                                    "shrink_warm_misses", "replayed",
                                    "regain_outcome",
                                )
                            }),
                            file=sys.stderr,
                        )
                    print(json.dumps(rec))
                    sys.stdout.flush()
            except Exception:
                traceback.print_exc(file=sys.stderr)
        if (
            rec is not None
            and "_tpu" in rec.get("metric", "")
            and remaining() > 180
        ):
            try:  # public-API PDE row — best-effort, never fatal
                pde = _try_pde(timeout_s=int(max(120, remaining() * 0.35)))
                if pde:
                    rec.update(pde)
            except Exception:
                traceback.print_exc(file=sys.stderr)
            try:  # second headline (GMG) — best-effort, never fatal
                gmg = _try_gmg(timeout_s=int(max(120, remaining() - 60)))
                if gmg:
                    rec.update(gmg)
            except Exception:
                traceback.print_exc(file=sys.stderr)
            if remaining() > 150:
                try:  # quantum evolution row — best-effort, never fatal
                    q = _try_quantum(timeout_s=int(max(90, remaining() - 30)))
                    if q:
                        rec.update(q)
                except Exception:
                    traceback.print_exc(file=sys.stderr)
            if remaining() > 150:
                try:  # AMG north-star row — best-effort, never fatal
                    amg = _try_amg(timeout_s=int(max(90, remaining() - 30)))
                    if amg:
                        rec.update(amg)
                except Exception:
                    traceback.print_exc(file=sys.stderr)
    except Exception:
        traceback.print_exc(file=sys.stderr)
    finally:
        if rec is not None and "_tpu" in rec.get("metric", ""):
            # live hardware measurement: append to the committed evidence
            # log so later wedged-tunnel runs can still surface it
            rec["source"] = "live"
            _log_hw_record(rec)
        else:
            # tunnel wedged at capture time: surface the freshest LOGGED
            # hardware record, clearly labeled as such, with the live
            # fallback preserved alongside (VERDICT r3 #4). Stale numbers
            # substitute ONLY for a wedged tunnel: a healthy cpu-only
            # probe means this machine has no TPU, and a live tunnel with
            # a failed worker means a code regression — both keep the
            # live line. A passed-then-failed probe re-checks once to
            # distinguish a mid-run wedge from a worker crash.
            if status == "tpu":
                # no budget to confirm a mid-run wedge -> don't substitute
                status = _probe_tpu(min(60, remaining())) if remaining() > 20 else "tpu"
            logged = (
                _freshest_session_record()
                if status == "dead" and "PALLAS_AXON_POOL_IPS" in os.environ
                else None  # no tunnel configured / broken env: live line stands
            )
            max_age = session_log_max_age_s  # parsed at main() entry
            if logged is not None:
                age_s = time.time() - logged["ts"]
                if age_s > max_age:
                    print(
                        f"bench: session-log record is {age_s:.0f}s old "
                        "(> max age); keeping the live line",
                        file=sys.stderr,
                    )
                    logged = None
            if logged is not None:
                live = rec
                rec = {k: v for k, v in logged.items() if k != "iso"}
                rec.pop("ts")
                rec["source"] = "session-log"
                rec["age_s"] = round(age_s)
                # distinct name so a naive last-line parser can never
                # mistake a logged record for a live one (ADVICE r4)
                if not rec.get("metric", "").endswith("_logged"):
                    rec["metric"] = rec.get("metric", "") + "_logged"
                if live is not None:
                    rec["live_fallback"] = {
                        "metric": live.get("metric"),
                        "value": live.get("value"),
                        "vs_baseline": live.get("vs_baseline"),
                    }
        if rec is None:
            rec = {
                "metric": "cg_iters_per_s_pde_none",
                "value": 0.0,
                "unit": "iters/s",
                "vs_baseline": 0.0,
            }
        print(json.dumps(rec))
        sys.stdout.flush()
        # the session log gets a record for EVERY run — probe timeouts and
        # all — so the round artifact chain never goes dark again
        _log_session_record(rec, status, t_start)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--worker":
        worker(sys.argv[2])
    elif len(sys.argv) > 1 and sys.argv[1] == "--probe":
        probe()
    else:
        main()
